"""paddle_tpu.optimizer (mirrors paddle.optimizer)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta,
    RMSProp, Lamb, LarsMomentum, Ftrl, FtrlOptimizer, Dpsgd, DpsgdOptimizer,
    DecayedAdagrad, DecayedAdagradOptimizer, ExponentialMovingAverage,
)
