"""Optimizers.

Parity surface: reference python/paddle/optimizer/ (v2 API) over
operators/optimizers/*.cc kernels. Each optimizer defines a *pure*
per-parameter update ``_pure_update(p, g, lr, slots...) -> (new_p, slots...)``;
the eager ``step()`` runs it jit-cached per parameter shape, and the
functional training path (paddle_tpu.jit.TrainStep) tree-maps the same
function inside one compiled XLA program — the analog of the reference
running one fused optimizer kernel per parameter
(e.g. operators/optimizers/adam_op.cu).
"""
from __future__ import annotations

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from ..regularizer import L1Decay, L2Decay
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "LarsMomentum",
]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._name = name
        if isinstance(weight_decay, float):
            self._weight_decay = L2Decay(weight_decay)
        else:
            self._weight_decay = weight_decay
        # slot store: name -> {id(param): jnp array}
        self._accumulators: dict = {}
        self._aux_state: dict = {}
        self._jit_cache: dict = {}

    # -- lr -----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    # -- slots --------------------------------------------------------------
    def _slot_names(self):
        return []

    def _init_slot(self, name, p):
        return jnp.zeros_like(p._data)

    def _get_slots(self, p):
        out = []
        for name in self._slot_names():
            store = self._accumulators.setdefault(name, {})
            if id(p) not in store:
                store[id(p)] = self._init_slot(name, p)
            out.append(store[id(p)])
        return out

    def _set_slots(self, p, values):
        for name, v in zip(self._slot_names(), values):
            self._accumulators[name][id(p)] = v

    # -- update -------------------------------------------------------------
    @staticmethod
    def _pure_update(p, g, lr, *slots, **hyper):
        raise NotImplementedError

    def _hyper(self, p):
        """Per-call static hyperparams (dict)."""
        return {}

    def _regularized_grad(self, p, g):
        reg = p.regularizer if p.regularizer is not None else self._weight_decay
        if isinstance(reg, L2Decay) and not self._decoupled_wd():
            return g + reg.coeff * p._data
        if isinstance(reg, L1Decay):
            return g + reg.coeff * jnp.sign(p._data)
        return g

    def _decoupled_wd(self):
        return False

    def _fused_supported(self) -> bool:
        """Does this optimizer have a flat-buffer fused step
        (ops/fused_optimizer.py)? Opt-in via FLAGS_fused_optimizer."""
        return False

    def step(self):
        from ..core.native import fused_optimizer as _fused_flag

        params_grads = []
        for p in self._parameter_list or []:
            if p.grad is None or not getattr(p, "trainable", True):
                continue
            params_grads.append((p, p.grad))
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        if _fused_flag[0] and self._fused_supported():
            # FLAGS_fused_optimizer: ONE device dispatch over flat
            # dtype-homogeneous buckets (persistent flat m/v) instead of
            # a per-parameter jit call each — falls through to the
            # unfused loop when the param set isn't coverable
            from ..ops.fused_optimizer import fused_eager_step

            if fused_eager_step(self, params_grads, lr):
                self._post_step()
                return
        for p, g in params_grads:
            garr = g._data if isinstance(g, Tensor) else g
            garr = self._regularized_grad(p, garr.astype(p._data.dtype))
            slots = self._get_slots(p)
            hyper = self._hyper(p)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            fn = self._jitted_update(tuple(sorted(hyper.items())))
            out = fn(p._data, garr, jnp.asarray(plr, dtype=jnp.float32), *slots)
            new_p, new_slots = out[0], out[1:]
            p._data = new_p
            self._set_slots(p, new_slots)
        self._post_step()

    def _post_step(self):
        pass

    def _jitted_update(self, hyper_items):
        key = hyper_items
        fn = self._jit_cache.get(key)
        if fn is None:
            hyper = dict(hyper_items)
            cls_update = type(self)._pure_update

            def run(p, g, lr, *slots):
                out = cls_update(p, g, lr, *slots, **hyper)
                return out if isinstance(out, tuple) else (out,)

            fn = jax.jit(run)
            self._jit_cache[key] = fn
        return fn

    # -- API parity ----------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import SymbolicTensor, default_main_program

        if isinstance(loss, SymbolicTensor):
            # static mode: register a training directive on the program that
            # OWNS the loss (the reference's optimizer appends grad+update
            # ops to that ProgramDesc; Executor.run performs them per call)
            prog = getattr(getattr(loss._expr, "op", None), "program", None) \
                or default_main_program()
            prog.train_specs.append((self, loss))
            return None, []
        # eager: the reference's dygraph minimize HARVESTS grads already
        # produced by loss.backward() (Optimizer.backward in dygraph mode
        # only collects param._grad_ivar()); it never runs autograd
        # itself. Call loss.backward() first, exactly like the reference.
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.grad = None

    clear_gradients = clear_grad

    def state_dict(self):
        fused = getattr(self, "_fused_state", None)
        if fused is not None and getattr(self, "_slots_stale", False):
            # flush the fused path's flat m/v buffers back into the
            # per-param slot mirrors so checkpoints see current state
            fused.sync_slots(self)
            self._slots_stale = False
        state = {}
        for name, store in self._accumulators.items():
            for p in self._parameter_list or []:
                if id(p) in store:
                    pname = p.name or f"param_{id(p)}"
                    state[f"{pname}.{name}"] = Tensor(store[id(p)])
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        for k, v in self._aux_state.items():
            state[k] = v
        return state

    def set_state_dict(self, state_dict):
        for name in self._slot_names():
            store = self._accumulators.setdefault(name, {})
            for p in self._parameter_list or []:
                pname = p.name or f"param_{id(p)}"
                key = f"{pname}.{name}"
                if key in state_dict:
                    v = state_dict[key]
                    store[id(p)] = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        # loaded slots supersede any fused flat buffers; rebuild lazily
        self._fused_state = None
        self._slots_stale = False

    @property
    def _param_groups(self):
        return self._parameter_list


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    @staticmethod
    def _pure_update(p, g, lr):
        return (p - lr.astype(p.dtype) * g,)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False, rescale_grad=1.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)

    def _slot_names(self):
        return ["velocity"]

    def _hyper(self, p):
        return {"mu": self._momentum, "nesterov": self._use_nesterov}

    @staticmethod
    def _pure_update(p, g, lr, v, mu, nesterov):
        lr = lr.astype(p.dtype)
        nv = mu * v + g
        if nesterov:
            np_ = p - (g + mu * nv) * lr
        else:
            np_ = p - nv * lr
        return np_, nv


class LarsMomentum(Momentum):
    """LARS (reference operators/optimizers/lars_momentum_op.cc)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon

    def _hyper(self, p):
        return {"mu": self._momentum, "lars_coeff": self._lars_coeff,
                "lars_wd": self._lars_wd, "eps": self._epsilon}

    @staticmethod
    def _pure_update(p, g, lr, v, mu, lars_coeff, lars_wd, eps):
        lr = lr.astype(p.dtype)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = lr * lars_coeff * p_norm / (eps + g_norm + lars_wd * p_norm + 1e-12)
        nv = mu * v + local_lr * (g + lars_wd * p)
        return p - nv, nv


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = float(beta1 if not isinstance(beta1, Tensor) else beta1.item())
        self._beta2 = float(beta2 if not isinstance(beta2, Tensor) else beta2.item())
        self._epsilon = float(epsilon)
        self._multi_precision = bool(multi_precision)

    def _slot_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _init_slot(self, name, p):
        if name == "beta1_pow":
            return jnp.asarray(self._beta1, dtype=jnp.float32)
        if name == "beta2_pow":
            return jnp.asarray(self._beta2, dtype=jnp.float32)
        # multi_precision (reference adam_op MultiPrecision path): fp32
        # master moments for low-precision params — zeros_like would
        # silently give bf16/fp16 params bf16/fp16 moments, losing the
        # fp32 accumulation multi_precision=True asks for
        if self._multi_precision and jnp.dtype(p._data.dtype).itemsize < 4:
            return jnp.zeros(p._data.shape, jnp.float32)
        return jnp.zeros_like(p._data)

    def _fused_supported(self):
        return type(self) in (Adam, AdamW)

    def _hyper(self, p):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon}

    @staticmethod
    def _pure_update(p, g, lr, m1, m2, b1p, b2p, b1, b2, eps):
        lr = lr.astype(jnp.float32)
        nm1 = b1 * m1 + (1 - b1) * g
        nm2 = b2 * m2 + (1 - b2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        np_ = p - (lr_t * nm1 / (jnp.sqrt(nm2) + eps)).astype(p.dtype)
        return np_, nm1, nm2, b1p * b1, b2p * b2


class AdamW(Adam):
    """Decoupled weight decay (reference python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decoupled_wd(self):
        return True

    def _hyper(self, p):
        coeff = self._coeff
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            coeff = 0.0
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon, "coeff": coeff}

    @staticmethod
    def _pure_update(p, g, lr, m1, m2, b1p, b2p, b1, b2, eps, coeff):
        lr = lr.astype(jnp.float32)
        p = p * (1.0 - lr * coeff).astype(p.dtype)
        return Adam._pure_update(p, g, lr, m1, m2, b1p, b2p, b1, b2, eps)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)

    def _slot_names(self):
        return ["moment", "inf_norm", "beta1_pow"]

    def _init_slot(self, name, p):
        if name == "beta1_pow":
            return jnp.asarray(self._beta1, dtype=jnp.float32)
        return jnp.zeros_like(p._data)

    def _hyper(self, p):
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon}

    @staticmethod
    def _pure_update(p, g, lr, m, inf, b1p, b1, b2, eps):
        lr = lr.astype(jnp.float32)
        nm = b1 * m + (1 - b1) * g
        ninf = jnp.maximum(b2 * inf, jnp.abs(g))
        np_ = p - ((lr / (1 - b1p)) * nm / (ninf + eps)).astype(p.dtype)
        return np_, nm, ninf, b1p * b1


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._init_acc = float(initial_accumulator_value)

    def _slot_names(self):
        return ["moment"]

    def _init_slot(self, name, p):
        return jnp.full_like(p._data, self._init_acc)

    def _hyper(self, p):
        return {"eps": self._epsilon}

    @staticmethod
    def _pure_update(p, g, lr, m, eps):
        lr = lr.astype(p.dtype)
        nm = m + jnp.square(g)
        return p - lr * g / (jnp.sqrt(nm) + eps), nm


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = float(epsilon), float(rho)

    def _slot_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _hyper(self, p):
        return {"eps": self._epsilon, "rho": self._rho}

    @staticmethod
    def _pure_update(p, g, lr, asg, asu, eps, rho):
        lr = lr.astype(p.dtype)
        nasg = rho * asg + (1 - rho) * jnp.square(g)
        update = -jnp.sqrt(asu + eps) / jnp.sqrt(nasg + eps) * g
        nasu = rho * asu + (1 - rho) * jnp.square(update)
        return p + lr * update, nasg, nasu


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = float(rho), float(epsilon)
        self._momentum, self._centered = float(momentum), bool(centered)

    def _slot_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _hyper(self, p):
        return {"rho": self._rho, "eps": self._epsilon, "mom": self._momentum,
                "centered": self._centered}

    @staticmethod
    def _pure_update(p, g, lr, ms, mg, mo, rho, eps, mom, centered):
        lr = lr.astype(p.dtype)
        nms = rho * ms + (1 - rho) * jnp.square(g)
        if centered:
            nmg = rho * mg + (1 - rho) * g
            denom = nms - jnp.square(nmg) + eps
        else:
            nmg = mg
            denom = nms + eps
        nmo = mom * mo + lr * g / jnp.sqrt(denom)
        return p - nmo, nms, nmg, nmo


class Lamb(Optimizer):
    """LAMB (reference python/paddle/optimizer/lamb.py, lamb_op.cc)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)
        self._lamb_wd = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _fused_supported(self):
        return type(self) is Lamb

    def _slot_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _init_slot(self, name, p):
        if name == "beta1_pow":
            return jnp.asarray(self._beta1, dtype=jnp.float32)
        if name == "beta2_pow":
            return jnp.asarray(self._beta2, dtype=jnp.float32)
        return jnp.zeros_like(p._data)

    def _hyper(self, p):
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return {"b1": self._beta1, "b2": self._beta2, "eps": self._epsilon, "wd": wd}

    @staticmethod
    def _pure_update(p, g, lr, m1, m2, b1p, b2p, b1, b2, eps, wd):
        lr = lr.astype(jnp.float32)
        nm1 = b1 * m1 + (1 - b1) * g
        nm2 = b2 * m2 + (1 - b2) * jnp.square(g)
        mhat = nm1 / (1 - b1p)
        vhat = nm2 / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - (lr * trust * r).astype(p.dtype), nm1, nm2, b1p * b1, b2p * b2


class Ftrl(Optimizer):
    """FTRL — Follow The Regularized Leader (reference
    python/paddle/fluid/optimizer.py FtrlOptimizer over
    operators/optimizers/ftrl_op.h: squared/linear accumulators, l1
    shrinkage, lr_power schedule)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, regularization, grad_clip,
                         name)
        self._l1 = float(l1)
        self._l2 = float(l2)
        self._lr_power = float(lr_power)

    def _slot_names(self):
        return ["squared_accum", "linear_accum"]

    def _hyper(self, p):
        return {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power}

    @staticmethod
    def _pure_update(p, g, lr, s_acc, l_acc, l1, l2, lr_power):
        lr = lr.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        new_acc = s_acc + g32 * g32
        if lr_power == -0.5:
            l_acc = l_acc + g32 - (jnp.sqrt(new_acc) - jnp.sqrt(s_acc)) / lr * p32
            y = jnp.sqrt(new_acc) / lr + 2.0 * l2
        else:
            l_acc = l_acc + g32 - (new_acc ** -lr_power
                                   - s_acc ** -lr_power) / lr * p32
            y = new_acc ** -lr_power / lr + 2.0 * l2
        x = l1 * jnp.sign(l_acc) - l_acc
        pre_shrink = x / y
        new_p = jnp.where(jnp.abs(l_acc) > l1, pre_shrink, 0.0)
        return new_p.astype(p.dtype), new_acc, l_acc


FtrlOptimizer = Ftrl


class Dpsgd(Optimizer):
    """DP-SGD — differentially private SGD (reference
    python/paddle/fluid/optimizer.py DpsgdOptimizer over
    operators/optimizers/dpsgd_op.h): per-tensor L2 clip to ``clip``, one
    gaussian noise sample scaled by 1/batch_size per update. The noise
    comes from the framework RNG (seeded, reproducible) instead of the
    reference's time(NULL)-seeded minstd engine."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._clip = float(clip)
        self._batch_size = float(batch_size)
        self._sigma = float(sigma)
        self._dp_update = None

    def step(self):
        from ..framework import random as grandom

        if self._dp_update is None:
            clip, bs = self._clip, self._batch_size

            @jax.jit
            def upd(p, g, lr, noise):
                g32 = g.astype(jnp.float32)
                l2 = jnp.sqrt(jnp.sum(jnp.square(g32)))
                scale = jnp.where(l2 > clip, l2 / clip, 1.0)
                step_ = lr * (g32 / scale + noise / bs)
                return (p.astype(jnp.float32) - step_).astype(p.dtype)

            self._dp_update = upd
        params_grads = [(p, p.grad) for p in self._parameter_list or []
                        if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        for p, g in params_grads:
            garr = g._data if isinstance(g, Tensor) else g
            noise = self._sigma * jax.random.normal(grandom.next_key(), ())
            p._data = self._dp_update(p._data, garr, lr, noise)


DpsgdOptimizer = Dpsgd


class DecayedAdagrad(Optimizer):
    """Decayed Adagrad (reference fluid/optimizer.py DecayedAdagradOptimizer
    over operators/optimizers/decayed_adagrad_op.h): moment decays instead
    of accumulating forever."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, regularization, grad_clip,
                         name)
        self._decay = float(decay)
        self._epsilon = float(epsilon)

    def _slot_names(self):
        return ["moment"]

    def _hyper(self, p):
        return {"decay": self._decay, "eps": self._epsilon}

    @staticmethod
    def _pure_update(p, g, lr, moment, decay, eps):
        lr = lr.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        m = decay * moment + (1.0 - decay) * g32 * g32
        new_p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(m) + eps)
        return new_p.astype(p.dtype), m


DecayedAdagradOptimizer = DecayedAdagrad


class ExponentialMovingAverage:
    """EMA of parameters with bias correction (reference fluid/optimizer.py
    ExponentialMovingAverage: EMA_t = decay*EMA_{t-1} + (1-decay)*theta_t,
    applied as EMA_t / (1 - decay^t); apply()/restore() swap the shadow
    values in and out, and apply_guard() is the context form)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._ema: dict = {}
        self._backup: dict = {}
        self._params: list = []
        self._step = 0

    def update(self, parameters=None):
        from ..framework.core import Parameter

        if parameters is not None:
            self._params = list(parameters)
        elif not self._params:
            raise ValueError("ExponentialMovingAverage.update needs "
                             "parameters on the first call")
        self._step += 1
        d = self._decay
        if self._thres_steps is not None:
            # reference: decay = min(decay, (1+steps)/(10+steps))
            t = float(self._thres_steps() if callable(self._thres_steps)
                      else self._step)
            d = min(d, (1.0 + t) / (10.0 + t))
        for p in self._params:
            if not isinstance(p, Parameter) and not hasattr(p, "_data"):
                continue
            prev = self._ema.get(id(p))
            cur = p._data.astype(jnp.float32)
            self._ema[id(p)] = (d * prev + (1.0 - d) * cur
                                if prev is not None else (1.0 - d) * cur)

    def apply(self, need_restore=True):
        corr = 1.0 - self._decay ** max(self._step, 1)
        self._backup = {}
        for p in self._params:
            ema = self._ema.get(id(p))
            if ema is None:
                continue
            if need_restore:
                self._backup[id(p)] = p._data
            p._data = (ema / corr).astype(p._data.dtype)

    def restore(self):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}

    def apply_guard(self, need_restore=True):
        from contextlib import contextmanager

        @contextmanager
        def guard():
            self.apply(need_restore)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return guard()
