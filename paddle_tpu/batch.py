"""Legacy reader-decorator paddle.batch (reference python/paddle/batch.py:18).

Kept for parity with pre-DataLoader ingestion code; new code should use
paddle.io.DataLoader, which prefetches onto the device.
"""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap an item-level reader into a batch-level reader."""
    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer, got %r"
                         % (batch_size,))

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
