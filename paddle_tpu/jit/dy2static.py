"""dygraph→static AST translation.

Parity: reference ProgramTranslator + AST transformers
(python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:768,
ifelse_transformer.py, loop_transformer.py, logical_transformer.py).

TPU-native: instead of rewriting to ConditionalBlock/While *ops*, the
transformers rewrite data-dependent Python control flow into runtime
run_ifelse / run_while helpers that dispatch to jax.lax.cond /
jax.lax.while_loop when the condition is traced, and fall back to plain
Python control flow when it is concrete — the same transformed source
serves eager debugging and jit compilation.

Scope (documented): `if`/`elif`/`else`, `while`, `and`/`or`/`not` inside
conditions, and `for i in range(...)` are translated. Constructs that
cannot be made trace-safe (`break`/`continue`/`return` under a traced
condition, `range(traced_n)`, shape-changing loop vars, single-branch
assignments used after a traced if) raise Dy2StaticError with a precise
message instead of silently freezing a branch — the failure mode VERDICT
r2 flagged for the bare-trace to_static.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["convert_to_static", "run_ifelse", "run_while",
           "convert_logical_and", "convert_logical_or", "convert_logical_not",
           "convert_range", "Dy2StaticError", "UNDEFINED"]

_JST = "_paddle_jst"  # name this module is bound to inside transformed code


class Dy2StaticError(RuntimeError):
    pass


class _Undefined:
    """Marker for names not defined at a converted construct's entry
    (reference dygraph_to_static UndefinedVar)."""

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


# ---------------------------------------------------------------------------
# runtime helpers called by transformed code
# ---------------------------------------------------------------------------

def _raw(x):
    return x._data if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_raw(x), jax.core.Tracer)


def _concrete_bool(x):
    return bool(np.asarray(_raw(x)))


def _to_arrays(vals):
    return tuple(_raw(v) for v in vals)


def _rewrap(arrays, template):
    return tuple(Tensor(a) if isinstance(t, Tensor) else a
                 for a, t in zip(arrays, template))


def lookup(fn):
    """Read a possibly-unbound enclosing-scope name."""
    try:
        return fn()
    except (NameError, UnboundLocalError):
        return UNDEFINED


def run_ifelse(pred, true_fn, false_fn, get_args, name=""):
    """Transformed `if`: branch fns take and return the live-out tuple."""
    if not _is_traced(pred):
        args = get_args()
        return tuple(true_fn(*args) if _concrete_bool(pred)
                     else false_fn(*args))

    init = get_args()
    undef = {i for i, v in enumerate(init) if isinstance(v, _Undefined)}

    def check(out, in_arrays):
        out = tuple(out)
        for i in undef:
            # a branch must overwrite every entering-undefined var; passing
            # the sentinel through unchanged means it did not.
            if isinstance(out[i], _Undefined) or out[i] is in_arrays[i] or \
                    (isinstance(out[i], Tensor) and out[i]._data is in_arrays[i]):
                raise Dy2StaticError(
                    f"to_static: a variable in traced if-statement "
                    f"'{name}' is assigned in only one branch but used "
                    "after the if — assign it in both branches (or before "
                    "the if)")
        return _to_arrays(out)

    def tf(arrays):
        return check(true_fn(*_rewrap(arrays, init)), arrays)

    def ff(arrays):
        return check(false_fn(*_rewrap(arrays, init)), arrays)

    # UNDEFINED leaves cannot cross lax.cond: substitute a 0-d sentinel;
    # check() above guarantees the branches overwrite them or we raise.
    init_arrays = tuple(jnp.zeros(()) if isinstance(a, _Undefined) else a
                        for a in _to_arrays(init))
    p = jnp.reshape(jnp.asarray(_raw(pred)), ()).astype(bool)
    out = jax.lax.cond(p, tf, ff, init_arrays)
    return _rewrap(out, init)


def run_while(cond_fn, body_fn, get_args, name=""):
    """Transformed `while`: cond/body take and return the loop-var tuple."""
    init = tuple(get_args())
    first = cond_fn(*init)
    if not _is_traced(first):
        vars_ = init
        while _concrete_bool(cond_fn(*vars_)):
            vars_ = tuple(body_fn(*vars_))
        return vars_

    for v in init:
        if isinstance(v, _Undefined):
            raise Dy2StaticError(
                f"to_static: a variable used by traced while-loop '{name}' "
                "is not defined before the loop — initialize it first")

    def c(arrays):
        r = cond_fn(*_rewrap(arrays, init))
        return jnp.reshape(jnp.asarray(_raw(r)), ()).astype(bool)

    def b(arrays):
        out = _to_arrays(tuple(body_fn(*_rewrap(arrays, init))))
        fixed = []
        for i, (o, v) in enumerate(zip(out, arrays)):
            osh = tuple(getattr(o, "shape", ()))
            vsh = tuple(getattr(v, "shape", ()))
            if osh != vsh:
                raise Dy2StaticError(
                    f"to_static: while-loop '{name}' variable #{i} changes "
                    f"shape across iterations ({vsh} → {osh}) — XLA While "
                    "requires loop-invariant shapes")
            if hasattr(o, "astype") and hasattr(v, "dtype") and \
                    o.dtype != v.dtype:
                o = o.astype(v.dtype)
            fixed.append(o)
        return tuple(fixed)

    init_arrays = tuple(jnp.asarray(a) for a in _to_arrays(init))
    out = jax.lax.while_loop(c, b, init_arrays)
    return _rewrap(out, init)


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return rhs_fn() if _concrete_bool(l) else l
    r = rhs_fn()
    return Tensor(jnp.logical_and(jnp.asarray(_raw(l)).astype(bool),
                                  jnp.asarray(_raw(r)).astype(bool)))


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return l if _concrete_bool(l) else rhs_fn()
    r = rhs_fn()
    return Tensor(jnp.logical_or(jnp.asarray(_raw(l)).astype(bool),
                                 jnp.asarray(_raw(r)).astype(bool)))


def convert_logical_not(x_fn):
    x = x_fn()
    if not _is_traced(x):
        return not _concrete_bool(x)
    return Tensor(jnp.logical_not(jnp.asarray(_raw(x)).astype(bool)))


def convert_range(*args):
    if any(_is_traced(a) for a in args):
        raise Dy2StaticError(
            "to_static: `for ... in range(traced_value)` cannot be "
            "unrolled — rewrite as a while-loop over a counter, or use "
            "paddle.static.nn.while_loop")
    return range(*(int(np.asarray(_raw(a))) for a in args))


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names assigned directly within a statement list (no nested defs)."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _loaded(nodes):
    v = _LoadedNames()
    for s in nodes:
        v.visit(s)
    return v.names


class _EscapeFinder(ast.NodeVisitor):
    """break/continue/return belonging to THIS block (not nested loops or
    nested function defs)."""

    def __init__(self, skip_loops):
        self.found = None
        self._skip_loops = skip_loops

    def visit_Break(self, node):
        self.found = self.found or "break"

    def visit_Continue(self, node):
        self.found = self.found or "continue"

    def visit_Return(self, node):
        self.found = self.found or "return"

    def visit_While(self, node):
        if not self._skip_loops:
            self.generic_visit(node)

    def visit_For(self, node):
        if not self._skip_loops:
            self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _escape_in(stmts, skip_loops):
    v = _EscapeFinder(skip_loops)
    for s in stmts:
        v.visit(s)
    return v.found


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _args_of(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n) for n in names], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _jst_attr(name):
    return ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _lookup_expr(n):
    """`_paddle_jst.lookup(lambda: x)` — tolerates unbound names."""
    return ast.Call(func=_jst_attr("lookup"),
                    args=[ast.Lambda(args=_empty_args(),
                                     body=ast.Name(id=n, ctx=ast.Load()))],
                    keywords=[])


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
        ctx=ast.Load()))


def _src_of(node):
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


class Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals=frozenset()):
        self.counter = 0
        # names local to the converted function (params + anything
        # assigned): used to keep modules/builtins read in a while-test
        # (e.g. `while paddle.sum(x) > 0`) out of the loop-carried state
        self.fn_locals = set(fn_locals)

    def _fresh(self, base):
        self.counter += 1
        return f"__jst_{base}{self.counter}"

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = ast.Call(
                func=_jst_attr(fn),
                args=[ast.Lambda(args=_empty_args(), body=v),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(ast.Call(
                func=_jst_attr("convert_logical_not"),
                args=[ast.Lambda(args=_empty_args(), body=node.operand)],
                keywords=[]), node)
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if _escape_in(node.body, skip_loops=True) or \
                _escape_in(node.orelse, skip_loops=True):
            return node  # return/break/continue in a branch: keep python

        live = sorted(n for n in (_assigned(node.body) | _assigned(node.orelse))
                      if not n.startswith("__jst_"))
        t_name = self._fresh("iftrue")
        f_name = self._fresh("iffalse")

        def branch(name, body):
            return ast.FunctionDef(
                name=name, args=_args_of(live),
                body=(list(body) or [ast.Pass()]) + [_ret_tuple(live)],
                decorator_list=[])

        get_lambda = ast.Lambda(
            args=_empty_args(),
            body=ast.Tuple(elts=[_lookup_expr(n) for n in live],
                           ctx=ast.Load()))
        call = ast.Call(
            func=_jst_attr("run_ifelse"),
            args=[node.test,
                  ast.Name(id=t_name, ctx=ast.Load()),
                  ast.Name(id=f_name, ctx=ast.Load()),
                  get_lambda, ast.Constant(value=_src_of(node.test))],
            keywords=[])
        if live:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in live],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        out = [branch(t_name, node.body), branch(f_name, node.orelse), assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def visit_While(self, node):
        self.generic_visit(node)
        if _escape_in(node.body, skip_loops=True) or node.orelse:
            return node

        live = sorted(n for n in
                      (_assigned(node.body) |
                       (_loaded([node.test]) & self.fn_locals))
                      if not n.startswith("__jst_"))
        c_name = self._fresh("whilecond")
        b_name = self._fresh("whilebody")
        cond_fn = ast.FunctionDef(
            name=c_name, args=_args_of(live),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=b_name, args=_args_of(live),
            body=list(node.body) + [_ret_tuple(live)], decorator_list=[])
        get_lambda = ast.Lambda(
            args=_empty_args(),
            body=ast.Tuple(elts=[_lookup_expr(n) for n in live],
                           ctx=ast.Load()))
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in live],
                ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("run_while"),
                args=[ast.Name(id=c_name, ctx=ast.Load()),
                      ast.Name(id=b_name, ctx=ast.Load()),
                      get_lambda, ast.Constant(value=_src_of(node.test))],
                keywords=[]))
        out = [cond_fn, body_fn, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def visit_For(self, node):
        self.generic_visit(node)
        if isinstance(node.iter, ast.Call) and \
                isinstance(node.iter.func, ast.Name) and \
                node.iter.func.id == "range":
            node.iter = ast.copy_location(
                ast.Call(func=_jst_attr("convert_range"),
                         args=node.iter.args, keywords=[]), node.iter)
            ast.fix_missing_locations(node)
        return node


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_CONVERT_CACHE: dict = {}


def convert_to_static(fn: Callable) -> Callable:
    """AST-convert a function or bound method; returns the original when no
    source is available (builtins, C functions, exec'd code)."""
    bound_self = getattr(fn, "__self__", None)
    raw_fn = fn.__func__ if bound_self is not None else fn

    cached = _CONVERT_CACHE.get(raw_fn)
    if cached is None:
        cached = _convert_raw(raw_fn)
        _CONVERT_CACHE[raw_fn] = cached
    if bound_self is not None:
        return cached.__get__(bound_self, type(bound_self))
    return cached


def _convert_raw(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    fn_locals = set()
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        fdef.decorator_list = []  # drop @to_static etc. to avoid recursion
        a = fdef.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                    ([a.vararg] if a.vararg else []) +
                    ([a.kwarg] if a.kwarg else [])):
            fn_locals.add(arg.arg)
        fn_locals |= _assigned(fdef.body)
    new_tree = Dy2StaticTransformer(fn_locals).visit(tree)
    ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree,
                       filename=f"<dy2static:{getattr(fn, '__name__', 'fn')}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        return fn
    import paddle_tpu.jit.dy2static as _self

    glb = dict(fn.__globals__)
    glb[_JST] = _self
    if fn.__closure__:
        # converted code loses the closure: bind freevars as globals
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    return functools.wraps(fn)(new_fn)
