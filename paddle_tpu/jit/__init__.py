"""paddle_tpu.jit — eager→compiled bridge.

This is the TPU-native replacement for BOTH reference worlds:
- ``paddle.jit.to_static`` (dygraph_to_static ProgramTranslator,
  reference python/paddle/fluid/dygraph/dygraph_to_static/) — here there is
  no AST rewriting: jax traces the eager code directly, so ``to_static`` is
  "functionalize + jax.jit".
- the static Program+Executor pipeline — a traced function IS the program.

Key primitives:
- ``state(layer)`` → (params, buffers) dicts of raw jax arrays.
- ``functional_call(layer, params, buffers, *args)`` → (out, new_buffers):
  runs ``layer.forward`` with the given arrays bound in place of its
  Parameters/buffers. Buffer mutation (BatchNorm running stats) is captured
  and returned instead of leaking tracers.
- ``TrainStep(model, loss_fn, optimizer)`` → one fused XLA program per
  (shape-set): forward + backward + optimizer update, the analog of the
  reference executor running the whole ProgramDesc in one go.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..analysis import sanitizers as _san
from ..core.native import fast_step as _fast_step
from ..core.native import sanitize as _sanitize
from ..framework.core import AsyncLoss, Parameter, Tensor
from ..nn.layer.layers import Layer
from ..resilience import faults as _faults
from ..resilience import sentinel as _sentinel

__all__ = ["state", "functional_call", "to_static", "TrainStep", "not_to_static",
           "ProgramTranslator", "TracedLayer", "TranslatedLayer",
           "set_code_level", "set_verbosity",
           "InputSpec", "save", "load"]


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def state(layer: Layer):
    params = {k: p._data for k, p in layer.named_parameters()}
    buffers = {k: b._data for k, b in layer.named_buffers() if b is not None}
    return params, buffers


def _named_state_tensors(layer: Layer):
    out = {}
    for k, p in layer.named_parameters():
        out[k] = p
    for k, b in layer.named_buffers():
        if b is not None:
            out[k] = b
    return out


def functional_call(layer: Layer, params: Dict[str, Any], buffers: Dict[str, Any],
                    *args, training: Optional[bool] = None, **kwargs):
    """Run layer.forward with arrays bound into its Parameters/buffers.

    Thread-unsafe by design (same as the reference's global tracer state);
    call within one trace at a time.
    """
    tensors = _named_state_tensors(layer)
    saved = {}
    saved_training = None
    try:
        for name, arr in {**params, **buffers}.items():
            t = tensors.get(name)
            if t is None:
                raise KeyError(f"no parameter/buffer named {name}")
            saved[name] = t._data
            t._data = arr if not isinstance(arr, Tensor) else arr._data
        if training is not None:
            saved_training = [(l, l.training) for l in layer.sublayers(include_self=True)]
            for l, _ in saved_training:
                l.training = training
        out = layer(*args, **kwargs)
        new_buffers = {name: tensors[name]._data for name in buffers}
        return out, new_buffers
    finally:
        for name, arr in saved.items():
            tensors[name]._data = arr
        if saved_training:
            for l, was in saved_training:
                l.training = was


def _tree_tensor_to_array(x):
    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def _tree_array_to_tensor(x):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, (jax.Array,)) or hasattr(v, "dtype") else v, x)


class StaticFunction:
    """Result of to_static: jit-compiled callable with .forward parity.

    Data-dependent Python control flow in the wrapped code is AST-converted
    (dy2static.convert_to_static) to lax.cond/lax.while_loop before
    tracing — the reference ProgramTranslator's role
    (dygraph_to_static/program_translator.py:768). Conversion is best
    effort per function: code without retrievable source traces as-is.
    """

    def __init__(self, fn_or_layer, input_spec=None, build_strategy=None):
        from .dy2static import convert_to_static

        self._input_spec = input_spec
        if isinstance(fn_or_layer, Layer):
            self._layer = fn_or_layer
            self._fn = None
            self._orig_call = fn_or_layer.forward  # pre-conversion, bound
            try:
                converted = convert_to_static(fn_or_layer.forward)
                if converted is not type(fn_or_layer).forward:
                    # bind converted forward on the instance (shadows the
                    # class method for this layer only)
                    object.__setattr__(fn_or_layer, "forward", converted)
            except Exception:
                pass  # conversion is best-effort; plain trace still works
        else:
            self._layer = None
            self._orig_call = fn_or_layer
            try:
                self._fn = convert_to_static(fn_or_layer)
            except Exception:
                self._fn = fn_or_layer
        self._compiled = None

    def _make_compiled(self):
        if self._layer is not None:
            layer = self._layer

            def pure(params, buffers, training, args, kwargs):
                out, new_buf = functional_call(layer, params, buffers, *args,
                                               training=training, **kwargs)
                return _tree_tensor_to_array(out), new_buf

            self._compiled = jax.jit(pure, static_argnums=(2,))
        else:
            fn = self._fn

            def pure_fn(args, kwargs):
                args = _tree_array_to_tensor(args)
                kwargs = _tree_array_to_tensor(kwargs)
                return _tree_tensor_to_array(fn(*args, **kwargs))

            self._compiled = jax.jit(pure_fn)

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator._enabled:
            # ProgramTranslator().enable(False): run the ORIGINAL python
            # eagerly (no AST conversion, no jit) so breakpoints/prints in
            # user code fire — reference program_translator.py semantics.
            return self._orig_call(*args, **kwargs)
        if self._compiled is None:
            self._make_compiled()
        arr_args = _tree_tensor_to_array(args)
        arr_kwargs = _tree_tensor_to_array(kwargs)
        if self._layer is not None:
            params, buffers = state(self._layer)
            out, new_buf = self._compiled(params, buffers, self._layer.training,
                                          arr_args, arr_kwargs)
            # write back mutated buffers eagerly
            tensors = _named_state_tensors(self._layer)
            for name, arr in new_buf.items():
                tensors[name]._data = arr
            return _tree_array_to_tensor(out)
        return _tree_array_to_tensor(self._compiled(arr_args, arr_kwargs))

    # Layer-protocol passthrough
    def __getattr__(self, item):
        if self._layer is not None:
            return getattr(self._layer, item)
        return getattr(self._fn, item)


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """paddle.jit.to_static parity (decorator or call)."""
    if function is None:
        return functools.partial(to_static, input_spec=input_spec,
                                 build_strategy=build_strategy)
    return StaticFunction(function, input_spec, build_strategy)


def not_to_static(fn):
    return fn


class TrainStep:
    """Fused forward+backward+update as one compiled XLA program.

    ``step(*batch)`` runs the whole training step on device and writes the
    updated params/slots back into the eager model. This is the performance
    path — the analog of ParallelExecutor running the rewritten program
    (reference executor.py:998) — while plain eager backward mirrors dygraph.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate: bool = True, grad_postprocess: Optional[Callable] = None,
                 sentinel=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.grad_postprocess = grad_postprocess
        # optional in-jit health sentinel (paddle_tpu.resilience): verdict
        # + trip counter carried as device state, update gated on it
        self._sentinel_cfg = (_sentinel.normalize_config(sentinel)
                              if sentinel else None)
        self.sentinel_state = (_sentinel.init_state()
                               if self._sentinel_cfg is not None else None)
        self._step_count = 0
        self._param_names = [k for k, _ in model.named_parameters()]
        self._params = {k: p for k, p in model.named_parameters()}
        # materialize slots eagerly in deterministic order
        self._slot_values = {}
        for k in self._param_names:
            p = self._params[k]
            self._slot_values[k] = list(self.optimizer._get_slots(p))
        self._hyper = {k: tuple(sorted(self.optimizer._hyper(self._params[k]).items()))
                       for k in self._param_names}
        self._compiled = None
        # fast-step (FLAGS_fast_step) state: donated-buffers jit, cached
        # buffer-tensor refs, cached device lr scalar, lazy optimizer-slot
        # sync marker
        self._compiled_fast = None
        self._buffer_tensors: Dict[str, Tensor] = {}
        self._lr_cache = (None, None)
        # guardian lr_backoff multiplier (scale_lr); 1.0 = untouched
        self._lr_scale = 1.0
        self._slots_dirty = False
        # FLAGS_sanitize: batch aval signatures already compiled — a new
        # one is a recompile; the explainer names the differing leaf
        self._batch_sigs: list = []

    def _build(self):
        model = self.model
        loss_fn = self.loss_fn
        opt = self.optimizer
        param_names = self._param_names
        hyper = self._hyper
        pure_update = type(opt)._pure_update
        grad_post = self.grad_postprocess

        sentinel_cfg = self._sentinel_cfg

        # FLAGS_fused_optimizer (read at build time): run the whole
        # Adam/AdamW update as one flat-buffer pass per dtype bucket
        # (ops/fused_optimizer.py) instead of the per-param loop below —
        # same slot layout, same checkpoint shape, fused execution.
        from ..core.native import fused_optimizer as _fused_opt_flag
        from ..monitor.stats import FUSED_OPTIMIZER_STEPS as _fused_gauge

        use_fused = (_fused_opt_flag[0]
                     and type(opt).__name__ in ("Adam", "AdamW")
                     and opt._slot_names() == ["moment1", "moment2",
                                               "beta1_pow", "beta2_pow"])
        self._use_fused = use_fused
        self._fused_gauge = _fused_gauge
        if use_fused:
            from ..ops.fused_optimizer import fused_update_from_slots

        # loss_fn contract: loss_fn(run_model, *batch_tensors) -> loss Tensor,
        # where run_model(*model_inputs) executes the params-bound model.
        def step_impl(params, slots, buffers, lr, batch, sent_state):
            def loss_of(params):
                args = _tree_array_to_tensor(batch)
                captured = dict(buffers)

                def run_model(*xs, **kw):
                    out, new_buf = functional_call(model, params, captured, *xs,
                                                   training=True, **kw)
                    captured.update(new_buf)
                    return out

                loss = loss_fn(run_model, *args)
                return (loss._data if isinstance(loss, Tensor) else loss), captured

            (loss, new_buffers), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            if grad_post is not None:
                grads = grad_post(grads)
            if use_fused:
                new_params, new_slots = fused_update_from_slots(
                    opt, param_names, params, grads, slots, lr, hyper)
            else:
                new_params = {}
                new_slots = {}
                for k in param_names:
                    h = dict(hyper[k])
                    out = pure_update(params[k],
                                      grads[k].astype(params[k].dtype),
                                      jnp.asarray(lr, jnp.float32),
                                      *slots[k], **h)
                    if not isinstance(out, tuple):
                        out = (out,)
                    new_params[k] = out[0]
                    new_slots[k] = list(out[1:])
            if sent_state is not None:
                # in-jit health verdict + GradScaler-style skip gate
                # (resilience.sentinel): a tripped step is a no-op
                gnorm = _sentinel.global_grad_norm(grads)
                sent_state = _sentinel.update(sent_state, loss, gnorm,
                                              sentinel_cfg)
                trip = sent_state["last_trip"]
                new_params = _sentinel.gate(trip, new_params, params)
                new_slots = _sentinel.gate(trip, new_slots, slots)
                new_buffers = _sentinel.gate(trip, new_buffers, buffers)
            return new_params, new_slots, new_buffers, loss, sent_state

        # pure step exposed for K-steps-in-one-jit timing (bench.py) and
        # custom outer loops — keeps the historical 5-arg/4-output
        # contract (no sentinel state); _compiled is the per-call dispatch
        # path, _compiled_fast additionally donates the buffer tree
        # (FLAGS_fast_step)
        self._step_impl = (
            lambda p, s, b, lr, batch: step_impl(p, s, b, lr, batch,
                                                 None)[:4])
        self._compiled = jax.jit(step_impl, donate_argnums=(0, 1))
        self._compiled_fast = jax.jit(step_impl, donate_argnums=(0, 1, 2))
        self._buffer_tensors = {k: b for k, b in self.model.named_buffers()
                                if b is not None}

    def __call__(self, *batch):
        if self._compiled is None:
            self._build()
        if _faults.ENABLED[0]:
            # fault-injection hook (FLAGS_fault_inject) — see
            # resilience.faults; one list-index check when idle
            batch = _faults.FAULTS.on_train_step(self._step_count, batch)
        self._step_count += 1
        if getattr(self, "_use_fused", False):
            self._fused_gauge.add()
        if _fast_step[0]:
            return self._call_fast(batch)
        params = {k: self._params[k]._data for k in self._param_names}
        buffers = {k: b._data for k, b in self.model.named_buffers() if b is not None}
        lr = self.optimizer.get_lr() * self._lr_scale
        arr_batch = _tree_tensor_to_array(batch)
        donated = None
        if _sanitize[0]:
            self._note_batch_sig(arr_batch)
            donated = (params, {k: list(v)
                                for k, v in self._slot_values.items()})
        new_params, new_slots, new_buffers, loss, self.sentinel_state = \
            self._compiled(params, self._slot_values, buffers, lr, arr_batch,
                           self.sentinel_state)
        if donated is not None:
            _san.tombstone_tree(donated)
        for k in self._param_names:
            self._params[k]._data = new_params[k]
            self._slot_values[k] = new_slots[k]
            self.optimizer._set_slots(self._params[k], new_slots[k])
        tensors = _named_state_tensors(self.model)
        for name, arr in new_buffers.items():
            tensors[name]._data = arr
        return Tensor(loss)

    def _call_fast(self, batch):
        """FLAGS_fast_step path: the bench device loop as framework code.

        Per step: pointer-read the device state (no module-tree walks),
        dispatch the donated step (params AND slots AND buffers — nothing
        is double-buffered), pointer-write the new arrays back into the
        same eager tensors, and return the loss WITHOUT blocking — the
        AsyncLoss handle syncs (and bumps step_async_syncs) only when the
        user reads it. Optimizer slot mirrors are synced lazily
        (:meth:`sync`), since ``_set_slots`` walks per-param dicts the
        step itself never reads."""
        params = {k: self._params[k]._data for k in self._param_names}
        buffers = {k: t._data for k, t in self._buffer_tensors.items()}
        lr = self.optimizer.get_lr() * self._lr_scale
        if self._lr_cache[0] != lr:
            # device-cache the lr scalar: a python-float jit arg is a
            # fresh host->device transfer every step
            self._lr_cache = (lr, jnp.float32(lr))
        arr_batch = _tree_tensor_to_array(batch)
        donated = None
        if _sanitize[0]:
            self._note_batch_sig(arr_batch)
            donated = (params, {k: list(v)
                                for k, v in self._slot_values.items()},
                       buffers)
        new_params, new_slots, new_buffers, loss, self.sentinel_state = \
            self._compiled_fast(params, self._slot_values, buffers,
                                self._lr_cache[1], arr_batch,
                                self.sentinel_state)
        if donated is not None:
            _san.tombstone_tree(donated)
        for k in self._param_names:
            self._params[k]._data = new_params[k]
            self._slot_values[k] = new_slots[k]
        for name, arr in new_buffers.items():
            self._buffer_tensors[name]._data = arr
        self._slots_dirty = True
        out = AsyncLoss(loss)
        if self.sentinel_state is not None:
            out.health = {"trip": self.sentinel_state["last_trip"],
                          "trips": self.sentinel_state["trips"]}
        return out

    def scale_lr(self, scale: float) -> None:
        """Set the ABSOLUTE learning-rate multiplier (TrainGuardian's
        post-rollback backoff). The lr enters the compiled step as a
        traced scalar, so rescaling never recompiles; optimizer
        schedules keep their shape, scaled."""
        self._lr_scale = float(scale)

    def _note_batch_sig(self, arr_batch):
        """FLAGS_sanitize recompile explainer: a batch aval signature not
        seen before means jax recompiles the step — diff it against the
        nearest compiled one and emit a sanitize.recompile span."""
        sig = _san.aval_signature(arr_batch)
        if sig in self._batch_sigs:
            return
        if self._batch_sigs:
            _san.note_recompile("TrainStep", sig, self._batch_sigs)
        self._batch_sigs.append(sig)

    def sync(self):
        """Flush lazily-deferred state mirrors (optimizer slot dicts) so
        host-side readers — optimizer.state_dict(), checkpoint save — see
        the current device state. Called automatically by hapi Model.fit
        at epoch boundaries and by Model.save."""
        if self._slots_dirty:
            for k in self._param_names:
                self.optimizer._set_slots(self._params[k],
                                          self._slot_values[k])
            self._slots_dirty = False


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity (reference jit/api.py save): persists
    - ``path.pdparams`` — the state_dict (eager reload), and, when
      ``input_spec`` is given,
    - ``path.pdmodel`` / ``path.pdiparams`` / ``path.pdmeta.json`` — a
      versioned StableHLO inference artifact (static/export.py) servable
      by paddle_tpu.inference.Predictor with no model code."""
    from ..framework.io import save as _save

    if isinstance(layer, StaticFunction):
        layer = layer._layer
    _save(layer.state_dict(), path + ".pdparams")

    if input_spec:
        import numpy as np

        from ..static.export import export_callable, write_artifacts

        params, buffers = state(layer)
        keys = sorted(params) + sorted(buffers)
        n_params = len(params)
        arrays = [params[k] for k in sorted(params)] + \
                 [buffers[k] for k in sorted(buffers)]

        def pure(state_list, *feeds):
            p = dict(zip(sorted(params), state_list[:n_params]))
            b = dict(zip(sorted(buffers), state_list[n_params:]))
            out, _ = functional_call(layer, p, b, *[Tensor(f) for f in feeds],
                                     training=False)
            return _tree_tensor_to_array(out)

        examples = [np.zeros(tuple(1 if (s is None or int(s) < 0) else int(s)
                                   for s in spec.shape),
                             dtype=spec.dtype)
                    for spec in input_spec]
        data, st, meta = export_callable(
            pure, arrays, examples,
            feed_names=[spec.name or f"x{i}"
                        for i, spec in enumerate(input_spec)])
        write_artifacts(path, data, st, meta)


def load(path, **configs):
    """Reference jit.load: returns a TranslatedLayer when jit.save
    artifacts exist at ``path``; falls back to the raw state dict."""
    import os

    if os.path.exists(path + ".pdmodel"):
        return TranslatedLayer(path)
    from ..framework.io import load as _load

    return _load(path + ".pdparams")


class ProgramTranslator:
    """Singleton switch for dy2static (reference
    dygraph_to_static/program_translator.py:768). enable(False) makes
    to_static functions run eagerly."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        type(self)._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return type(self)._enabled


def set_code_level(level=100, also_to_stdout=False):
    """Log transformed code at the given level (reference jit API); the
    AST translator logs through the standard logging module here."""
    import logging

    logging.getLogger("paddle_tpu.dy2static").setLevel(
        logging.DEBUG if level else logging.WARNING)


def set_verbosity(level=0, also_to_stdout=False):
    import logging

    logging.getLogger("paddle_tpu.dy2static").setLevel(
        logging.DEBUG if level else logging.WARNING)


class TranslatedLayer(Layer):
    """Layer reconstructed from jit.save artifacts, served through the
    compiled-program Predictor (reference dygraph/io.py TranslatedLayer)."""

    def __init__(self, path):
        super().__init__()
        from ..inference import Predictor

        self._predictor = Predictor(path)

    def forward(self, *inputs):
        arrs = [x.numpy() if isinstance(x, Tensor) else x for x in inputs]
        outs = [Tensor(jnp.asarray(o)) for o in self._predictor.run(arrs)]
        return outs[0] if len(outs) == 1 else tuple(outs)


class TracedLayer:
    """Trace a dygraph layer into a servable program (reference
    dygraph/jit.py TracedLayer): TracedLayer.trace -> (out, traced);
    traced(x) replays; save_inference_model exports."""

    def __init__(self, layer, input_spec):
        self._layer = layer
        self._input_spec = input_spec

    @staticmethod
    def trace(layer, inputs):
        out = layer(*inputs)
        spec = [InputSpec(list(x.shape), str(x.dtype)) for x in inputs]
        return out, TracedLayer(layer, spec)

    def __call__(self, *inputs):
        return self._layer(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._layer, path, input_spec=self._input_spec)
