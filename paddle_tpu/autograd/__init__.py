"""paddle_tpu.autograd — eager reverse-mode AD over the tape.

Analog of reference paddle.autograd (python/paddle/autograd/) backed by
imperative/basic_engine.cc; here the engine lives in framework.core.
"""
from __future__ import annotations

from ..framework.core import Tensor, apply_op, backward, grad, no_grad, enable_grad

__all__ = ["backward", "grad", "no_grad", "enable_grad", "PyLayer", "PyLayerContext"]

import jax


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable function.

    Parity: paddle.autograd.PyLayer
    (reference python/paddle/autograd/py_layer.py). ``forward``/``backward``
    are staticmethods over Tensors; we bridge them onto the tape with
    jax.custom_vjp semantics implemented manually.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework import core

        ctx = PyLayerContext()
        with core.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)

        tensor_args = tuple(a for a in args if isinstance(a, core.Tensor))
        needs_grad = core.is_grad_enabled() and any(
            not t.stop_gradient or t._grad_node is not None for t in tensor_args
        )
        if not needs_grad:
            return out

        def vjp_fn(cts):
            cts_t = tuple(core.Tensor(c) for c in (cts if isinstance(cts, tuple) else (cts,)))
            with core.no_grad():
                gin = cls.backward(ctx, *cts_t)
            gin = gin if isinstance(gin, (tuple, list)) else (gin,)
            gin_arrays = []
            gi = iter(gin)
            for a in args:
                if isinstance(a, core.Tensor):
                    g = next(gi, None)
                    gin_arrays.append(None if g is None else g._data)
            return tuple(gin_arrays)

        node = core.GradNode(
            vjp_fn,
            tensor_args,
            [(o._data.shape, o._data.dtype) for o in outs],
            multi,
            cls.__name__,
        )
        for i, o in enumerate(outs):
            o._grad_node = node
            o._out_index = i
            o.stop_gradient = False
        return out
