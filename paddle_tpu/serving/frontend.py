"""Multi-tenant OpenAI-style HTTP front end for the serving engine
(ISSUE 11) — the network surface of the "millions of users" layer.

    python -m paddle_tpu.serving.frontend --port 8000
    curl -s localhost:8000/v1/completions \\
         -H "Authorization: Bearer demo-key" \\
         -d '{"model":"gpt-tiny","prompt":"hello","max_tokens":16}'

Pure stdlib ``asyncio`` — no web framework: one event loop owns every
connection, parses a minimal HTTP/1.1 request per connection, and
bridges to the :class:`~paddle_tpu.serving.engine.InferenceEngine`
through the loop's executor (``submit`` may block on engine
backpressure; token streams are pumped from an executor thread into an
``asyncio.Queue``). The engine keeps its own scheduler thread — the
front end is a CLIENT of the engine, never a second writer to device
state.

Routes:

- ``POST /v1/completions`` — prompt (string or token-id list) →
  ``text_completion`` JSON, or Server-Sent Events when ``"stream":
  true`` (chunked transfer encoding, ``data: [DONE]`` terminator);
- ``POST /v1/chat/completions`` — ``messages`` flattened through a
  deterministic template (``role: content\\n`` + ``assistant:``), so a
  shared system prompt is a shared PREFIX the radix cache serves from
  blocks; SSE deltas when streaming;
- ``GET /v1/models`` — the single served model;
- ``GET /metrics`` — real Prometheus text exposition (ISSUE 15): every
  StatRegistry gauge with ``# HELP``/``# TYPE`` and sanitized names,
  every latency histogram (first-token, per-token, queue wait, decode
  tick, prefill chunk — recorded at the source) as cumulative
  ``_bucket{le=...}``/``_sum``/``_count`` series; rendered from
  registry snapshots so a scrape never blocks a scheduler tick.

Causal tracing (ISSUE 15): every generation request gets a
``monitor.TraceContext`` minted at admission; the flow-START event,
the WFQ ``frontend.queue_wait`` span and every downstream engine span
(prefill chunks, decode ticks, failover hops, completion) carry its
trace id, so chrome-trace renders one connected timeline per request
and ``tools/trace_report.py --section request`` prints the critical
path. Tracing off = token streams pinned bit-identical.

Tenancy & SLO scheduling: every request authenticates with
``Authorization: Bearer <api-key>`` against a :class:`Tenant` table.
Admission is a per-tenant token bucket (``rate`` req/s, ``burst``) plus
a ``max_streams`` concurrent-stream cap — exhaustion answers **429**
with ``Retry-After`` — and admitted requests queue into their tenant's
PRIORITY LANE. A single dispatcher drains lanes by weighted fair
queuing where a request's cost is its PREFILL CHUNK count
(``ceil(prompt_tokens / prefill_chunk)``): a gold-lane one-liner
overtakes a bronze-lane novella, but bronze retains its weight share —
long prompts cannot starve a lane, mirroring engine-side chunked
prefill (the PR-7 prefill-starvation verdict, measured end-to-end by
``tools/trace_report.py frontend_report`` from the ``frontend.request``
spans this module emits).

Structured output: ``response_format`` of ``{"type": "json_schema",
"json_schema": {...}}`` (or a ``regex`` key) compiles through
serving.constrained into a token-mask automaton riding the engine's
sampling program; the stream ends with ``finish_reason: "stop"`` when
the match completes and the body is guaranteed-parseable JSON.

Overload hardening (ISSUE 13) — the 429-vs-503 contract: **429** means
YOUR tenant broke its own admission contract (token bucket, stream cap)
and other tenants are unaffected; **503** + ``Retry-After`` means the
SERVER cannot take the work — engine queue saturated, the request's
``deadline_s`` expired before generation started (in the WFQ lane or in
the engine queue; ``frontend_load_sheds``), or the brownout ladder
(serving.overload) reached a shed rung for your lane. Deadlines
propagate END TO END: ``deadline_s`` in the body starts the clock at
HTTP admission, WFQ lane wait burns it, the ENGINE gets only the
remainder, and the response waits (`result`/SSE pumps) use the
remainder too instead of a hardcoded cap — a request that produced
tokens before expiring returns them with ``finish_reason "deadline"``
(or ``"timeout"`` when the wait itself lapsed), never a silent drop.
A client that DISCONNECTS mid-stream is detected by the read-side EOF
watcher and its engine request is cancelled, releasing its slot, paged
blocks and prefix-tree references.

``GET /healthz`` answers liveness (the loop is serving); ``GET
/readyz`` answers readiness — engine (or >= 1 router replica) alive,
block-pool headroom > 0, brownout ladder below its shed rungs — with
the failing checks in the 503 body. Mounting an
:class:`~paddle_tpu.serving.router.EngineRouter` instead of an engine
makes every route replica-aware; a lifecycle replacement that is still
RE-WARMING its prefix tree shows up in the replica checks (``warming``)
but is not counted ready, and an attached
:class:`~paddle_tpu.serving.lifecycle.ReplicaSupervisor`'s state
(target replica count, ladder positions) rides in ``checks.lifecycle``.
"""
from __future__ import annotations

import argparse
import asyncio
import collections
import json
import threading
import time
import uuid
from datetime import datetime, timezone
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..monitor.stats import (FAULTS_INJECTED, FRONTEND_429S,
                             FRONTEND_ACTIVE_STREAMS, FRONTEND_LOAD_SHEDS,
                             FRONTEND_QUEUE_WAIT_MS, FRONTEND_REQUESTS,
                             SERVING_QUEUE_WAIT_MS, prometheus_text,
                             stat_get)
from ..monitor.trace import emit_complete, emit_flow, recording, span
from ..monitor.tracectx import mint_trace
from ..resilience import faults as _faults
from .constrained import compile_constraint
from .engine import QueueFull

__all__ = ["ServingFrontend", "Tenant", "TokenBucket", "LANE_WEIGHTS"]

# default lane weights: a gold chunk is worth 4 bronze chunks of service
LANE_WEIGHTS = {"gold": 4.0, "silver": 2.0, "bronze": 1.0}


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.
    ``take()`` returns 0.0 on success or the seconds until a token will
    exist (the 429 Retry-After). Thread-safe — handlers run on the loop
    thread but tenants may be probed from tests/operators."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate if self.rate > 0 \
                else float("inf")


class Tenant:
    """One API key's admission contract: rate/burst token bucket,
    concurrent-stream cap, and the SLO lane its requests queue in."""

    def __init__(self, name: str, api_key: str, rate: float = 10.0,
                 burst: float = 20.0, max_streams: int = 8,
                 lane: str = "silver"):
        if lane not in LANE_WEIGHTS:
            raise ValueError(f"unknown lane {lane!r} "
                             f"(choose from {sorted(LANE_WEIGHTS)})")
        self.name = name
        self.api_key = api_key
        self.bucket = TokenBucket(rate, burst)
        self.max_streams = int(max_streams)
        self.lane = lane
        self._active = 0
        self._lock = threading.Lock()

    def acquire_stream(self) -> bool:
        with self._lock:
            if self._active >= self.max_streams:
                return False
            self._active += 1
        FRONTEND_ACTIVE_STREAMS.add(1)
        return True

    def release_stream(self) -> None:
        with self._lock:
            self._active -= 1
        FRONTEND_ACTIVE_STREAMS.add(-1)

    @property
    def active_streams(self) -> int:
        return self._active


class _WfqScheduler:
    """Weighted fair queuing over prefill chunks (loop-thread only).

    Each lane keeps a virtual finish tag; enqueue stamps the item with
    ``max(lane_v, global_v) + cost / weight`` and the dispatcher always
    serves the smallest tag — textbook WFQ, with cost measured in
    prefill chunks so service share is PROMPT WORK, not request count."""

    def __init__(self, weights: Dict[str, float]):
        self._weights = dict(weights)
        self._lanes: Dict[str, collections.deque] = {
            lane: collections.deque() for lane in weights}
        self._lane_v = {lane: 0.0 for lane in weights}
        self._vtime = 0.0
        self._ready = asyncio.Event()

    def put(self, lane: str, cost: float, item) -> None:
        start = max(self._vtime, self._lane_v[lane])
        finish = start + float(cost) / self._weights[lane]
        self._lane_v[lane] = finish
        self._lanes[lane].append((finish, item))
        self._ready.set()

    def __len__(self):
        return sum(len(q) for q in self._lanes.values())

    async def get(self):
        while True:
            best_lane = None
            for lane, q in self._lanes.items():
                if q and (best_lane is None
                          or q[0][0] < self._lanes[best_lane][0][0]):
                    best_lane = lane
            if best_lane is not None:
                finish, item = self._lanes[best_lane].popleft()
                self._vtime = max(self._vtime, finish)
                return item
            self._ready.clear()
            await self._ready.wait()


class _Job:
    """One admitted generation request waiting in its WFQ lane."""

    __slots__ = ("tenant", "kwargs", "future", "t_enqueued", "deadline_t")

    def __init__(self, tenant: Tenant, kwargs: dict, future,
                 deadline_t: Optional[float] = None):
        self.tenant = tenant
        self.kwargs = kwargs
        self.future = future
        self.t_enqueued = time.monotonic()
        self.deadline_t = deadline_t    # absolute monotonic, or None


class _Shed(Exception):
    """Server-side load shed (503 material): the request expired in the
    WFQ lane before the engine ever saw it."""


class _HttpError(Exception):
    def __init__(self, status: int, message: str, headers=None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or {}


_STATUS_TEXT = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
                404: "Not Found", 405: "Method Not Allowed",
                429: "Too Many Requests", 500: "Internal Server Error",
                503: "Service Unavailable"}


class ServingFrontend:
    """The asyncio HTTP server wrapping one InferenceEngine.

    ::

        fe = ServingFrontend(engine, tenants=[Tenant("acme", "sk-acme",
                                                     lane="gold")])
        fe.start()                      # loop thread; fe.port is bound
        ...
        fe.close()

    ``engine`` must carry a tokenizer (text prompts and constraints
    need the byte table). ``tenants`` defaults to a single open
    "default" tenant with key ``"demo-key"``.
    """

    def __init__(self, engine, tenants: Optional[List[Tenant]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 model_id: str = "paddle-tpu-gpt",
                 default_max_tokens: int = 64,
                 default_timeout_s: float = 600.0):
        if engine.tokenizer is None:
            raise ValueError("ServingFrontend needs an engine with a "
                             "tokenizer (InferenceEngine(tokenizer=...))")
        self.engine = engine            # an InferenceEngine OR EngineRouter
        self.host = host
        self.port = int(port)           # rewritten to the bound port
        self.model_id = model_id
        self.default_max_tokens = int(default_max_tokens)
        # response-wait cap for requests WITHOUT a deadline_s; requests
        # with one wait exactly their remaining budget instead
        self.default_timeout_s = float(default_timeout_s)
        # the brownout ladder rides in on the engine/router (engine
        # constructor arg overload=); None = no ladder, no admission
        # sheds, no token caps — the PR-11 front end exactly
        self._overload = getattr(engine, "overload", None)
        self._conn_seq = 0              # streaming-connection index
        #                                 (the conn_drop fault key)
        tenants = tenants if tenants is not None else [
            Tenant("default", "demo-key")]
        self.tenants: Dict[str, Tenant] = {t.api_key: t for t in tenants}
        self._chunk = engine.prefill_chunk or 64
        self._constraints: Dict[str, object] = {}   # schema/regex -> compiled
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        # built here, used only on the loop thread (asyncio.Event binds
        # its loop lazily on first wait, so off-loop construction is ok)
        self._wfq = _WfqScheduler(LANE_WEIGHTS)
        self._dispatcher: Optional[asyncio.Task] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self._closing = False

    # -- lifecycle -----------------------------------------------------------
    def start(self, timeout: float = 30.0) -> "ServingFrontend":
        """Run the server on a dedicated loop thread; returns once the
        socket is bound (``self.port`` holds the real port)."""
        self._thread = threading.Thread(target=self._run_loop,
                                        name="serving-frontend", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout):
            raise RuntimeError("frontend did not start in time")
        if self._startup_error is not None:
            raise RuntimeError("frontend failed to start") \
                from self._startup_error
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(self._serve())
        except BaseException as e:  # noqa: BLE001 — surface startup failures
            self._startup_error = e
            self._started.set()
        finally:
            loop.close()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._dispatcher = asyncio.ensure_future(self._dispatch())
        self._started.set()
        async with self._server:
            try:
                await self._server.serve_forever()
            except asyncio.CancelledError:
                pass
        self._dispatcher.cancel()

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting connections and join the loop thread (the
        engine is NOT shut down — it belongs to the caller)."""
        self._closing = True
        loop = self._loop
        if loop is not None and self._server is not None:
            def _stop():
                self._server.close()
                for task in asyncio.all_tasks(loop):
                    task.cancel()
            loop.call_soon_threadsafe(_stop)
        if self._thread is not None:
            self._thread.join(timeout)

    # -- dispatcher (WFQ lanes -> engine admission) --------------------------
    async def _dispatch(self) -> None:
        """Single drain of the fair-queued lanes: engine submission
        happens in the executor because a full engine queue BLOCKS —
        that backpressure paces the dispatcher, so lane order is
        preserved all the way into the engine."""
        loop = asyncio.get_running_loop()
        while True:
            job = await self._wfq.get()
            wait_ms = (time.monotonic() - job.t_enqueued) * 1e3
            if self._overload is not None:
                self._overload.observe_queue_wait(wait_ms)
            if job.deadline_t is not None:
                remaining = job.deadline_t - time.monotonic()
                if remaining <= 0:
                    # expired in the WFQ lane: shed before the engine
                    # spends anything on it (503 + Retry-After upstream)
                    if not job.future.done():
                        job.future.set_exception(_Shed(
                            "deadline expired while queued "
                            f"({wait_ms:.0f}ms in lane)"))
                    continue
                # the engine gets the REMAINING budget, not a fresh one
                job.kwargs["deadline_s"] = remaining
            try:
                req = await loop.run_in_executor(
                    None, lambda: self.engine.submit(**job.kwargs))
            except BaseException as e:  # noqa: BLE001 — fail THIS job only
                if not job.future.done():
                    job.future.set_exception(e)
                continue
            FRONTEND_QUEUE_WAIT_MS.add(int(wait_ms))
            SERVING_QUEUE_WAIT_MS.observe(wait_ms)
            if not job.future.done():
                job.future.set_result((req, wait_ms))

    # -- HTTP plumbing -------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            method, path, headers, body = await self._read_request(reader)
        except (asyncio.IncompleteReadError, ConnectionError,
                ValueError):
            writer.close()
            return
        status = 500
        tenant_name = "?"
        lane = "?"
        t0 = time.perf_counter()
        try:
            if path == "/v1/models" and method == "GET":
                status = await self._models(writer)
            elif path == "/metrics" and method == "GET":
                status = await self._metrics(writer)
            elif path == "/healthz" and method == "GET":
                status = await self._healthz(writer)
            elif path == "/readyz" and method == "GET":
                status = await self._readyz(writer)
            elif path in ("/v1/completions", "/v1/chat/completions"):
                if method != "POST":
                    raise _HttpError(405, "POST required")
                tenant = self._authenticate(headers)
                tenant_name, lane = tenant.name, tenant.lane
                status = await self._generate(
                    tenant, body, writer, reader,
                    chat=path == "/v1/chat/completions")
            elif path == "/v1/rank":
                if method != "POST":
                    raise _HttpError(405, "POST required")
                tenant = self._authenticate(headers)
                tenant_name, lane = tenant.name, tenant.lane
                status = await self._rank(body, writer)
            else:
                raise _HttpError(404, f"no route {path}")
        except _HttpError as e:
            status = e.status
            await self._send_json(writer, e.status,
                                  {"error": {"message": e.message,
                                             "type": "invalid_request_error"
                                             if e.status < 500 else
                                             "server_error"}},
                                  extra=e.headers)
        except (ConnectionError, asyncio.CancelledError):
            raise
        except BaseException as e:  # noqa: BLE001 — answer 500, keep serving
            status = 500
            try:
                await self._send_json(
                    writer, 500,
                    {"error": {"message": f"{type(e).__name__}: {e}",
                               "type": "server_error"}})
            except ConnectionError:
                pass
        finally:
            if path.startswith("/v1/c"):   # generation routes only
                with span("frontend.request", cat="frontend",
                          args={"tenant": tenant_name, "lane": lane,
                                "status": status, "path": path,
                                "ms": (time.perf_counter() - t0) * 1e3,
                                "prefix_hit_rate":
                                    stat_get("prefix_hit_rate")}):
                    pass
            try:
                await writer.drain()
            except ConnectionError:
                pass
            writer.close()

    @staticmethod
    async def _read_request(reader) -> Tuple[str, str, dict, bytes]:
        line = await reader.readline()
        if not line:
            raise ConnectionError("empty request")
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            raise ValueError(f"bad request line {line!r}")
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            hl = await reader.readline()
            if hl in (b"\r\n", b"\n", b""):
                break
            k, _, v = hl.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or "0")
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    async def _send_json(self, writer, status: int, obj: dict,
                         extra: Optional[dict] = None) -> None:
        payload = json.dumps(obj).encode("utf-8")
        headers = {"Content-Type": "application/json",
                   "Content-Length": str(len(payload)),
                   "Connection": "close"}
        headers.update(extra or {})
        writer.write(self._head(status, headers) + payload)
        await writer.drain()

    @staticmethod
    def _head(status: int, headers: dict) -> bytes:
        lines = [f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, '')}"]
        lines += [f"{k}: {v}" for k, v in headers.items()]
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    # -- routes --------------------------------------------------------------
    def _authenticate(self, headers: dict) -> Tenant:
        auth = headers.get("authorization", "")
        key = auth[7:].strip() if auth.lower().startswith("bearer ") else ""
        tenant = self.tenants.get(key)
        if tenant is None:
            raise _HttpError(401, "unknown or missing API key")
        return tenant

    async def _models(self, writer) -> int:
        await self._send_json(writer, 200, {
            "object": "list",
            "data": [{"id": self.model_id, "object": "model",
                      "owned_by": "paddle_tpu"}]})
        return 200

    async def _metrics(self, writer) -> int:
        """Prometheus text exposition 0.0.4 (ISSUE 15): every gauge with
        ``# HELP``/``# TYPE`` and sanitized names (the per-axis ``.``
        gauges become ``_``), every latency histogram as cumulative
        ``_bucket{le=...}``/``_sum``/``_count`` series. Renders from
        registry snapshots on the loop thread — the scrape never touches
        engine state, so it cannot block a scheduler tick."""
        payload = prometheus_text().encode("utf-8")
        writer.write(self._head(200, {
            "Content-Type": "text/plain; version=0.0.4",
            "Content-Length": str(len(payload)),
            "Connection": "close"}) + payload)
        await writer.drain()
        return 200

    # -- health (k8s-style liveness/readiness probes) ------------------------
    async def _healthz(self, writer) -> int:
        """Liveness: the loop answered, the process serves."""
        await self._send_json(writer, 200, {"status": "ok"})
        return 200

    async def _rank(self, body: bytes, writer) -> int:
        """POST /v1/rank (ISSUE 16): sparse features -> scores through
        the engine's sharded embedding tables. Body: ``{"slots":
        {name: [[ids...], ...]} | [[ids...], ...], "dense":
        [[floats...], ...]?}`` (a bare list binds to the single armed
        table). The jitted lookup+score runs in the executor — it holds
        no loop state and shares nothing with the scheduler thread."""
        if getattr(self.engine, "_ranker", None) is None and \
                not hasattr(self.engine, "rank"):
            raise _HttpError(404, "ranking not enabled on this server")
        try:
            req = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            raise _HttpError(400, f"bad JSON: {e}") from None
        slots = req.get("slots")
        if not slots:
            raise _HttpError(400, "missing 'slots'")
        ranker = getattr(self.engine, "_ranker", None)
        if isinstance(slots, list):
            if ranker is None or len(ranker.tables) != 1:
                raise _HttpError(400, "bare 'slots' list needs exactly "
                                      "one armed table; use {name: ids}")
            slots = {next(iter(ranker.tables)): slots}
        dense = req.get("dense")
        loop = asyncio.get_running_loop()
        try:
            scores = await loop.run_in_executor(
                None, lambda: self.engine.rank(slots, dense))
        except RuntimeError as e:
            raise _HttpError(404, str(e)) from None
        except (ValueError, TypeError, KeyError) as e:
            raise _HttpError(400, f"bad rank request: {e}") from None
        await self._send_json(writer, 200,
                              {"object": "rank",
                               "scores": [float(s) for s in scores]})
        return 200

    def _engine_checks(self) -> dict:
        e = self.engine
        checks: dict = {}
        if hasattr(e, "healthy_replicas"):          # EngineRouter
            healthy = e.healthy_replicas()
            checks["engine_alive"] = bool(healthy)
            # health() carries per-replica warming/draining flags — a
            # lifecycle replacement mid-re-warm is visible but NOT ready
            checks["replicas"] = {str(k): v for k, v in e.health().items()}
            heads = []
            for i in healthy:
                try:
                    heads.append(e.engine_for(i).pool_headroom())
                except KeyError:
                    continue        # removed between snapshot and read
            checks["pool_headroom"] = round(max(heads), 4) if heads else 0.0
            sup = getattr(e, "supervisor", None)
            if sup is not None:
                checks["lifecycle"] = sup.snapshot()
            # cross-host fleet membership (ISSUE 19): host id, role and
            # last-heartbeat age per replica, beside the lifecycle view
            fleet = getattr(e, "fleet_members", None)
            if callable(fleet):
                checks["fleet"] = {str(k): v for k, v in fleet().items()}
        else:
            checks["engine_alive"] = bool(e.alive)
            checks["pool_headroom"] = round(e.pool_headroom(), 4)
        if self._overload is not None:
            checks["brownout"] = self._overload.snapshot()
        return checks

    async def _readyz(self, writer) -> int:
        """Readiness: would a generation request admitted NOW be served?
        Engine (or at least one router replica) alive, block-pool
        headroom left, and the brownout ladder below its shed rungs."""
        checks = self._engine_checks()
        ready = checks["engine_alive"] and checks["pool_headroom"] > 0.0
        if self._overload is not None and self._overload.sheds("bronze"):
            ready = False           # shed rung: stop ADMITTING via the LB
        status = 200 if ready else 503
        await self._send_json(
            writer, status,
            {"status": "ok" if ready else "unready", "checks": checks},
            extra=None if ready else {"Retry-After": "2"})
        return status

    # -- generation ----------------------------------------------------------
    def _chat_prompt(self, messages) -> str:
        """Deterministic flattening: the shared system prompt becomes a
        shared radix-cache PREFIX across every conversation using it."""
        if not isinstance(messages, list) or not messages:
            raise _HttpError(400, "messages must be a non-empty list")
        parts = []
        for m in messages:
            role = str(m.get("role", "user"))
            parts.append(f"{role}: {m.get('content', '')}\n")
        parts.append("assistant:")
        return "".join(parts)

    def _constraint_for(self, body: dict):
        rf = body.get("response_format")
        if not rf:
            return None
        kind = rf.get("type")
        try:
            if kind == "json_schema":
                schema = rf.get("json_schema") or rf.get("schema")
                if isinstance(schema, dict) and "schema" in schema:
                    schema = schema["schema"]   # OpenAI nests it
                key = "s:" + json.dumps(schema, sort_keys=True)
                if key not in self._constraints:
                    self._constraints[key] = compile_constraint(
                        tokenizer=self.engine.tokenizer, json_schema=schema,
                        vocab_size=self.engine.cfg.vocab_size)
                return self._constraints[key]
            if kind == "regex":
                key = "r:" + rf["regex"]
                if key not in self._constraints:
                    self._constraints[key] = compile_constraint(
                        tokenizer=self.engine.tokenizer, regex=rf["regex"],
                        vocab_size=self.engine.cfg.vocab_size)
                return self._constraints[key]
            if kind in (None, "text"):
                return None
        except ValueError as e:
            raise _HttpError(400, f"bad response_format: {e}")
        raise _HttpError(400, f"unsupported response_format type {kind!r}")

    async def _generate(self, tenant: Tenant, raw: bytes, writer, reader,
                        chat: bool) -> int:
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise _HttpError(400, f"bad JSON body: {e}")
        # -- brownout shed (503, server-side): checked BEFORE the token
        # bucket so a shed never burns the tenant's own budget ----------
        if self._overload is not None and self._overload.sheds(tenant.lane):
            FRONTEND_LOAD_SHEDS.add(1)
            raise _HttpError(
                503, f"overloaded (brownout rung "
                     f"{self._overload.rung_name}): {tenant.lane} lane "
                     "admissions are shed",
                headers={"Retry-After": "2"})
        if chat:
            prompt_ids = self.engine.tokenizer.encode(
                self._chat_prompt(body.get("messages")))
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt_ids = np.asarray(prompt, np.int32)
            else:
                prompt_ids = self.engine.tokenizer.encode(str(prompt))
        if prompt_ids.size < 1:
            raise _HttpError(400, "empty prompt")
        # -- admission: token bucket, then the stream cap ------------------
        retry = tenant.bucket.take()
        if retry > 0:
            FRONTEND_429S.add(1)
            raise _HttpError(
                429, f"tenant {tenant.name} over rate limit",
                headers={"Retry-After": str(max(1, int(retry + 0.999)))})
        if not tenant.acquire_stream():
            FRONTEND_429S.add(1)
            raise _HttpError(
                429, f"tenant {tenant.name} at max_streams "
                     f"({tenant.max_streams})",
                headers={"Retry-After": "1"})
        FRONTEND_REQUESTS.add(1)
        try:
            return await self._generate_admitted(
                tenant, body, prompt_ids, writer, reader, chat)
        finally:
            tenant.release_stream()

    async def _generate_admitted(self, tenant, body, prompt_ids, writer,
                                 reader, chat: bool) -> int:
        max_toks = int(body.get("max_tokens", self.default_max_tokens))
        if self._overload is not None:
            # brownout rung 3: non-gold generations are capped — they
            # finish early instead of holding slots through the storm
            max_toks = self._overload.cap_max_tokens(tenant.lane, max_toks)
        kwargs = dict(
            prompt=prompt_ids,
            max_new_tokens=max_toks,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            constraint=self._constraint_for(body),
            timeout=60.0)
        # the deadline clock starts at HTTP admission: WFQ lane wait
        # burns it, the engine receives only the remainder (dispatcher),
        # and the response waits below use the remainder too
        deadline_t = None
        if body.get("deadline_s") is not None:
            deadline_t = time.monotonic() + float(body["deadline_s"])
        if kwargs["constraint"] is None:
            kwargs["eos_id"] = self.engine.tokenizer.eos_id
        # causal tracing (ISSUE 15): mint the request's trace context at
        # HTTP admission — the flow-START anchor every downstream span
        # (lane wait, prefill chunks, decode ticks, failover hops) chains
        # from. Minting never touches sampling: tracing-off token
        # streams are pinned bit-identical.
        ctx = mint_trace()
        kwargs["trace"] = ctx
        if recording():
            t = time.perf_counter()
            emit_flow("s", ctx.trace_id, t)
            emit_complete("frontend.admission", t, 0.0, cat="frontend",
                          args=ctx.args(tenant=tenant.name,
                                        lane=tenant.lane,
                                        prompt_tokens=int(prompt_ids.size)))
        cost = max(1.0, -(-int(prompt_ids.size) // self._chunk))
        fut = asyncio.get_running_loop().create_future()
        self._wfq.put(tenant.lane, cost,
                      _Job(tenant, kwargs, fut, deadline_t=deadline_t))
        try:
            req, wait_ms = await fut
        except QueueFull as e:
            FRONTEND_LOAD_SHEDS.add(1)
            raise _HttpError(503, f"engine queue saturated: {e}",
                             headers={"Retry-After": "1"})
        except _Shed as e:
            FRONTEND_LOAD_SHEDS.add(1)
            raise _HttpError(503, str(e), headers={"Retry-After": "1"})
        qw_args = {"tenant": tenant.name, "lane": tenant.lane,
                   "wait_ms": wait_ms,
                   "prompt_tokens": int(prompt_ids.size)}
        if recording():
            qw_args.update(ctx.args())
        with span("frontend.queue_wait", cat="frontend", args=qw_args,
                  flow=ctx.trace_id):
            pass
        rid = f"cmpl-{uuid.uuid4().hex[:20]}"
        created = int(datetime.now(timezone.utc).timestamp())
        if body.get("stream"):
            return await self._stream_response(req, writer, rid, created,
                                               chat, reader, deadline_t)
        loop = asyncio.get_running_loop()
        finish = None
        try:
            tokens = await loop.run_in_executor(
                None, lambda: req.result(timeout=self._wait_s(deadline_t)))
        except TimeoutError:
            # the WAIT lapsed (deadline or default cap): cancel so the
            # engine releases the slot/blocks, answer with what exists
            req.cancel()
            tokens = list(req.tokens)
            finish = "timeout"
        finish = finish or req.finish_reason
        if finish in ("deadline", "timeout") and not tokens:
            # expired before the first token: a shed, not a result —
            # 503 + Retry-After, never a silent empty 200
            FRONTEND_LOAD_SHEDS.add(1)
            raise _HttpError(503, "deadline exceeded before generation "
                                  "started", headers={"Retry-After": "1"})
        text = self.engine.tokenizer.decode(tokens, skip_special=True)
        choice = {"index": 0, "finish_reason": finish,
                  "logprobs": None}
        if chat:
            choice["message"] = {"role": "assistant", "content": text}
            obj_type = "chat.completion"
        else:
            choice["text"] = text
            obj_type = "text_completion"
        await self._send_json(writer, 200, {
            "id": rid, "object": obj_type, "created": created,
            "model": self.model_id, "choices": [choice],
            "usage": {"prompt_tokens": int(prompt_ids.size),
                      "completion_tokens": len(tokens),
                      "total_tokens": int(prompt_ids.size) + len(tokens)}})
        return 200

    def _wait_s(self, deadline_t: Optional[float]) -> float:
        """Response-wait budget: the request's REMAINING deadline, or
        the configured default for deadline-less requests."""
        if deadline_t is None:
            return self.default_timeout_s
        return max(1e-3, deadline_t - time.monotonic())

    @staticmethod
    async def _watch_disconnect(reader) -> None:
        """Resolves when the CLIENT goes away: EOF or reset on the
        connection's read side. Any stray pipelined bytes are drained
        and ignored — SSE clients do not speak mid-stream."""
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    return
        except ConnectionError:
            return

    # -- SSE streaming -------------------------------------------------------
    async def _stream_response(self, req, writer, rid: str, created: int,
                               chat: bool, reader,
                               deadline_t: Optional[float] = None) -> int:
        writer.write(self._head(200, {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Transfer-Encoding": "chunked",
            "Connection": "close"}))
        await writer.drain()
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()
        wait_s = self._wait_s(deadline_t)

        def pump():
            """Executor thread: blockingly iterate the token stream and
            hand text pieces to the loop (utf-8-safe via the engine's
            streaming detokenizer)."""
            try:
                try:
                    for piece in req.stream_text(timeout=wait_s):
                        loop.call_soon_threadsafe(queue.put_nowait,
                                                  ("piece", piece))
                    loop.call_soon_threadsafe(queue.put_nowait,
                                              ("done", req.finish_reason))
                except TimeoutError:
                    # the wait (deadline remainder) lapsed between
                    # tokens: cancel and close the stream cleanly
                    req.cancel()
                    loop.call_soon_threadsafe(queue.put_nowait,
                                              ("done", "timeout"))
            except BaseException as e:  # noqa: BLE001 — surface in-stream
                try:
                    loop.call_soon_threadsafe(queue.put_nowait, ("err", e))
                except RuntimeError:
                    pass                # loop already closed

        task = loop.run_in_executor(None, pump)
        # disconnect watcher: an SSE client that vanishes must CANCEL
        # its engine request (slot + paged blocks + prefix refs) instead
        # of leaving it decoding to nobody
        eof = asyncio.ensure_future(self._watch_disconnect(reader))
        # conn_drop chaos spec: the front end aborts this connection
        # after its first piece — the deterministic stand-in for the
        # vanished client above
        self._conn_seq += 1
        drop = _faults.ENABLED[0] \
            and _faults.FAULTS.take_conn(self._conn_seq) is not None
        if drop:
            FAULTS_INJECTED.add()
        sent = 0
        obj_type = "chat.completion.chunk" if chat else "text_completion"
        try:
            while True:
                getter = asyncio.ensure_future(queue.get())
                done, _ = await asyncio.wait(
                    {getter, eof}, return_when=asyncio.FIRST_COMPLETED)
                if eof in done and getter not in done:
                    getter.cancel()
                    raise ConnectionResetError("client disconnected "
                                               "mid-stream")
                kind, payload = await getter
                if kind == "piece":
                    if chat:
                        choice = {"index": 0, "finish_reason": None,
                                  "delta": {"content": payload}}
                    else:
                        choice = {"index": 0, "finish_reason": None,
                                  "text": payload}
                    await self._sse(writer, {
                        "id": rid, "object": obj_type, "created": created,
                        "model": self.model_id, "choices": [choice]})
                    sent += 1
                    if drop and sent >= 1:
                        writer.transport.abort()
                        raise ConnectionResetError("injected conn_drop")
                elif kind == "done":
                    choice = {"index": 0, "finish_reason": payload}
                    if chat:
                        choice["delta"] = {}
                    else:
                        choice["text"] = ""
                    await self._sse(writer, {
                        "id": rid, "object": obj_type, "created": created,
                        "model": self.model_id, "choices": [choice]})
                    await self._sse_raw(writer, b"data: [DONE]\n\n")
                    break
                else:
                    await self._sse(writer, {"error": {
                        "message": f"{type(payload).__name__}: {payload}"}})
                    break
            writer.write(b"0\r\n\r\n")      # chunked terminator
            await writer.drain()
        except ConnectionError:
            # client is gone: cancel so the engine evicts the stream and
            # returns its slot, paged blocks and prefix-tree references
            req.cancel()
        finally:
            eof.cancel()
            if not task.done():
                await asyncio.wait([task])
        return 200

    async def _sse(self, writer, obj: dict) -> None:
        await self._sse_raw(
            writer, b"data: " + json.dumps(obj).encode("utf-8") + b"\n\n")

    @staticmethod
    async def _sse_raw(writer, payload: bytes) -> None:
        writer.write(f"{len(payload):x}\r\n".encode("latin-1") + payload
                     + b"\r\n")
        await writer.drain()


# ==========================================================================
# python -m paddle_tpu.serving.frontend
# ==========================================================================

def _demo_engine(paged: bool = True, prefix: bool = True):
    """A gpt_tiny engine with the byte tokenizer — the zero-config demo
    target (swap in real weights by constructing ServingFrontend
    directly)."""
    import jax.numpy as jnp

    from ..models.gpt import gpt_init, gpt_tiny
    from .engine import InferenceEngine
    from .tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = gpt_tiny(seq_len=256, vocab_size=512, dtype=jnp.float32)
    params = gpt_init(cfg, seed=0)
    return InferenceEngine(cfg, params, n_slots=8, paged=paged,
                           block_size=16, prefill_chunk=64,
                           prefix_cache=prefix and paged, tokenizer=tok)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.serving.frontend",
        description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--api-key", default="demo-key",
                    help="single-tenant API key (use ServingFrontend "
                         "programmatically for a real tenant table)")
    ap.add_argument("--lane", default="silver",
                    choices=sorted(LANE_WEIGHTS))
    ap.add_argument("--rate", type=float, default=10.0)
    ap.add_argument("--no-prefix-cache", action="store_true")
    args = ap.parse_args(argv)

    engine = _demo_engine(prefix=not args.no_prefix_cache)
    fe = ServingFrontend(
        engine, tenants=[Tenant("default", args.api_key, rate=args.rate,
                                lane=args.lane)],
        host=args.host, port=args.port)
    fe.start()
    print(f"serving {fe.model_id} on http://{fe.host}:{fe.port} "
          f"(key: {args.api_key})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        fe.close()
        engine.shutdown(drain=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
