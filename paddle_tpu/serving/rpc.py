"""Thin stdlib RPC transport for the cross-host serving fleet (ISSUE 19),
hardened against real network failure (ISSUE 20).

One frame = ``PRPC`` magic + ``<I json_len, Q blob_len>`` + a JSON header
+ an optional binary blob. The header carries the method, scalar params,
and a manifest describing how the blob splits into named numpy arrays
(``{"name", "dtype", "shape", "nbytes"}`` each) — KV block rows ride the
blob raw, never JSON. The same frame shape serves requests and replies.

Design constraints, in order:

- **stdlib only** (socket/struct/json/threading/zlib) — the fleet must
  not grow a dependency the training side doesn't have.
- **Blocking request/response per connection.** The server runs one
  thread per connection, so a handler may legitimately block (the
  long-poll ``wait`` that streams tokens parks in ``req._cv.wait_for``
  server-side); the client keeps a small connection pool so one parked
  long-poll never delays a concurrent health probe.
- **Failure = exception, not hang.** Socket timeouts bound every call;
  a dead peer surfaces as :class:`RpcError` at the caller, which is the
  signal the fleet layer (serving/pod.py) turns into replica failover.

Reliability layer (ISSUE 20), every piece default-off-path:

- :class:`RetryPolicy` — idempotent-only retries with deterministic
  exponential backoff and capped attempt/deadline budgets
  (``rpc_retries``). Non-idempotent methods (``submit``/``adopt``)
  never retry: a replayed submit would double-decode a request.
- :class:`CircuitBreaker` — per-peer: ``threshold`` consecutive
  transport errors open it, every call then fast-fails without dialing
  until ``cooldown_s`` passes, after which exactly ONE half-open probe
  is let through (success closes, failure re-opens). A dead host costs
  one fast-failed call instead of a socket timeout per request.
  ``rpc_breaker_state`` gauges the breakers currently open;
  ``rpc.breaker_open`` spans mark each transition.
- **Deadline riding the frame header** — ``call(deadline_s=...)``
  stamps the remaining budget as ``deadline_ms``; the receiver sheds a
  frame whose budget is already gone at dispatch time instead of
  computing a result nobody will read (``rpc_deadline_sheds``).
- **Optional blob crc** — ``call(crc=True)`` adds a ``crc`` (zlib
  crc32 of the blob) the receiver verifies before decoding; a corrupt
  KV chunk surfaces as ``RpcRemoteError(etype="RpcCorruptFrame")``,
  never as silently-wrong cache rows.
- **Pool hygiene** — a socket whose call raised ANYWHERE (transport
  error, desynced response id, torn reply blob) is closed and dropped;
  only a fully-validated round trip returns its socket to the pool, so
  one torn reply can never poison the next call.

With no retry/breaker configured and no deadline passed, the frame
byte-stream and the call path are identical to ISSUE 19 — the off-path
cost is one ``is None`` check per call, and the fault hooks guard on
``faults.ENABLED[0]``.

Threading notes (GL003/GL004): the server's connection set and the
client's socket pool are the only cross-thread state, each guarded by
its own ``_lock``; sockets are checked out under the lock but all I/O
happens outside it, so no lock is ever held across a blocking call and
no second lock is ever taken while one is held.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..monitor.stats import (RPC_BREAKER_STATE, RPC_CALL_MS, RPC_CALLS,
                             RPC_DEADLINE_SHEDS, RPC_ERRORS, RPC_RETRIES)
from ..monitor.trace import emit_complete, recording
from ..resilience.faults import ENABLED as _FAULTS_ON
from ..resilience.faults import FAULTS as _FAULTS
from ..resilience.faults import net_partition_blocks

__all__ = ["RpcError", "RpcRemoteError", "RpcServer", "RpcClient",
           "RetryPolicy", "CircuitBreaker", "encode_arrays",
           "decode_arrays"]

_MAGIC = b"PRPC"
_HEAD = len(_MAGIC) + 12            # magic + <I json_len> + <Q blob_len>
MAX_HEADER_BYTES = 16 * 1024 * 1024
MAX_BLOB_BYTES = 512 * 1024 * 1024


class RpcError(RuntimeError):
    """Transport-level failure: dead peer, torn frame, timeout."""


class RpcRemoteError(RpcError):
    """The remote handler raised; ``etype`` names the remote type so the
    fleet layer can distinguish e.g. a remote QueueFull from a crash."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype


# -- array codec -------------------------------------------------------------
def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes               # bfloat16/fp8 names (jax dep)

        return np.dtype(getattr(ml_dtypes, name))


def encode_arrays(arrays: Dict[str, Any]) -> Tuple[list, bytes]:
    """(manifest, blob) for a dict of numpy arrays; order-preserving."""
    manifest, parts = [], []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        raw = a.tobytes()
        manifest.append({"name": str(name), "dtype": a.dtype.name,
                         "shape": list(a.shape), "nbytes": len(raw)})
        parts.append(raw)
    return manifest, b"".join(parts)


def decode_arrays(manifest, blob: bytes) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    off = 0
    for m in manifest or ():
        n = int(m["nbytes"])
        if off + n > len(blob):
            raise RpcError(f"torn blob: manifest wants {off + n} bytes, "
                           f"frame carries {len(blob)}")
        a = np.frombuffer(blob, dtype=_np_dtype(m["dtype"]),
                          count=n // max(1, _np_dtype(m["dtype"]).itemsize),
                          offset=off)
        out[str(m["name"])] = a.reshape([int(s) for s in m["shape"]])
        off += n
    if off != len(blob):
        raise RpcError(f"torn blob: {len(blob) - off} trailing bytes")
    return out


# -- framing -----------------------------------------------------------------
def _recvall(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            if buf:                    # mid-frame death: corruption, not
                raise RpcError(        # a clean between-frames close
                    f"truncated frame: peer closed after {len(buf)} of "
                    f"{n} bytes")
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _pack_frame(header: dict, blob: bytes = b"") -> bytes:
    payload = json.dumps(header, separators=(",", ":")).encode()
    return (_MAGIC + struct.pack("<IQ", len(payload), len(blob))
            + payload + blob)


def _send_frame(sock: socket.socket, header: dict, blob: bytes = b"") -> None:
    sock.sendall(_pack_frame(header, blob))


def _recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    head = _recvall(sock, _HEAD)
    if not head.startswith(_MAGIC):
        raise RpcError(f"bad frame magic {head[:4]!r}")
    jlen, blen = struct.unpack("<IQ", head[len(_MAGIC):])
    if jlen > MAX_HEADER_BYTES or blen > MAX_BLOB_BYTES:
        raise RpcError(f"oversized frame: header {jlen}B, blob {blen}B")
    try:
        header = json.loads(_recvall(sock, jlen))
    except (ValueError, UnicodeDecodeError) as e:
        raise RpcError(f"corrupt frame header: {e}") from e
    blob = _recvall(sock, blen) if blen else b""
    return header, blob


def _flip_byte(frame: bytes, jlen: int, blen: int) -> bytes:
    """Deterministic in-flight corruption (rpc_corrupt): XOR one byte
    with 0xFF — inside the blob when there is one (the crc path), else
    inside the JSON header (high bit set = invalid UTF-8, the
    torn-frame path)."""
    if blen > 0:
        off = _HEAD + jlen + blen // 2
    else:
        off = _HEAD + jlen // 2
    b = bytearray(frame)
    b[off] ^= 0xFF
    return bytes(b)


# -- reliability policy ------------------------------------------------------
class RetryPolicy:
    """Deterministic retry budget for IDEMPOTENT methods only.

    Backoff is exponential from ``backoff_s`` doubling per attempt,
    capped at ``backoff_max_s`` — no jitter, so chaos replays are
    bit-reproducible. ``submit``/``adopt`` are deliberately absent from
    the default method set: replaying one would double-decode a request
    on a peer that actually received the first copy.
    """

    IDEMPOTENT = frozenset({
        "hello", "health", "wait", "cancel", "warm", "prefill_export",
        "prefill_start", "export_range", "import_kv", "import_chunk",
        "ensure_replicas", "evacuate", "collect_flight",
    })

    def __init__(self, max_attempts: int = 3, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0, methods=None):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.methods = frozenset(methods) if methods is not None \
            else self.IDEMPOTENT

    def retryable(self, method: str) -> bool:
        return method in self.methods

    def backoff(self, attempt: int) -> float:
        """Pause before retry ``attempt`` (0-based)."""
        return min(self.backoff_max_s, self.backoff_s * (2 ** attempt))


BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2

# process-wide count of OPEN breakers behind the rpc_breaker_state gauge
_OPEN_LOCK = threading.Lock()
_OPEN_COUNT = [0]


def _note_breaker(delta: int) -> None:
    with _OPEN_LOCK:
        _OPEN_COUNT[0] = max(0, _OPEN_COUNT[0] + delta)
        RPC_BREAKER_STATE.set(_OPEN_COUNT[0])


class CircuitBreaker:
    """Per-peer circuit breaker: ``threshold`` CONSECUTIVE transport
    errors open it; while open, :meth:`allow` fast-fails every call
    until ``cooldown_s`` passes, then admits exactly one half-open
    probe. The probe's outcome closes or re-opens the breaker."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 peer: str = ""):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.peer = str(peer)
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consec = 0
        self._opened_t = 0.0
        self._probing = False

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now? (True also claims the single
        half-open probe slot when the cooldown has elapsed.)"""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN and not self._probing \
                    and time.monotonic() - self._opened_t >= self.cooldown_s:
                self._state = BREAKER_HALF_OPEN
                self._probing = True
                return True
            if self._state == BREAKER_HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def note_ok(self) -> None:
        with self._lock:
            was_open = self._state != BREAKER_CLOSED
            self._state = BREAKER_CLOSED
            self._consec = 0
            self._probing = False
        if was_open:
            _note_breaker(-1)

    def note_error(self) -> None:
        opened = False
        with self._lock:
            self._consec += 1
            if self._state == BREAKER_HALF_OPEN:
                self._state = BREAKER_OPEN      # failed probe: re-open
                self._opened_t = time.monotonic()
                self._probing = False
            elif self._state == BREAKER_CLOSED \
                    and self._consec >= self.threshold:
                self._state = BREAKER_OPEN
                self._opened_t = time.monotonic()
                opened = True
        if opened:
            _note_breaker(+1)
            if recording():
                emit_complete("rpc.breaker_open", time.perf_counter(), 0.0,
                              cat="serving",
                              args={"peer": self.peer,
                                    "consec_errors": self._consec})

    def __repr__(self):
        names = {BREAKER_CLOSED: "closed", BREAKER_HALF_OPEN: "half-open",
                 BREAKER_OPEN: "open"}
        return f"CircuitBreaker(peer={self.peer!r}, {names[self.state]})"


# -- server ------------------------------------------------------------------
class RpcServer:
    """One accept thread + one thread per connection, dispatching to a
    dict of handlers ``{method: fn(params, arrays) -> result}`` where a
    handler may return either a JSON-able result or a tuple ``(result,
    arrays)`` to ship binary payloads back. Handler exceptions become
    :class:`RpcRemoteError` at the caller; they never kill the server."""

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0):
        self._handlers = dict(handlers)
        self._listener = socket.create_server((host, int(port)))
        self.addr: Tuple[str, int] = self._listener.getsockname()[:2]
        self._lock = threading.Lock()          # guards _conns
        self._conns: set = set()
        self._closed_event = threading.Event()
        self._accepter = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True)
        self._accepter.start()

    def _accept_loop(self) -> None:
        while not self._closed_event.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:                    # listener closed: shutdown
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closed_event.is_set():
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rpc-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed_event.is_set():
                try:
                    header, blob = _recv_frame(conn)
                except (ConnectionError, OSError, RpcError, ValueError):
                    break                      # peer gone / torn frame
                t_recv = time.monotonic()
                resp, rblob = self._dispatch(header, blob, t_recv)
                try:
                    _send_frame(conn, resp, rblob)
                except (ConnectionError, OSError):
                    break
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, header: dict, blob: bytes,
                  t_recv: Optional[float] = None) -> Tuple[dict, bytes]:
        mid = header.get("id")
        method = header.get("method", "")
        if t_recv is None:
            t_recv = time.monotonic()
        # injected receiver-side delay (rpc_delay rides the header so
        # the claim stays in the CLIENT's per-peer call-index space)
        if _FAULTS_ON[0] and header.get("_inject_delay_s") is not None:
            time.sleep(float(header["_inject_delay_s"]))
        # deadline shed: the caller's remaining budget rode the header —
        # if it is gone by dispatch time, answer without computing
        dl_ms = header.get("deadline_ms")
        if dl_ms is not None \
                and (time.monotonic() - t_recv) * 1e3 >= float(dl_ms):
            RPC_DEADLINE_SHEDS.add(1)
            return ({"id": mid, "ok": False, "etype": "DeadlineExpired",
                     "error": f"frame budget {float(dl_ms):.1f}ms expired "
                     "before dispatch (shed)"}, b"")
        crc = header.get("crc")
        if crc is not None and zlib.crc32(blob) != int(crc):
            return ({"id": mid, "ok": False, "etype": "RpcCorruptFrame",
                     "error": "blob crc mismatch (corrupt in flight)"}, b"")
        fn = self._handlers.get(method)
        if fn is None:
            return ({"id": mid, "ok": False, "etype": "KeyError",
                     "error": f"no such method: {method!r}"}, b"")
        try:
            arrays = decode_arrays(header.get("blobs"), blob)
            out = fn(header.get("params") or {}, arrays)
        except Exception as e:  # noqa: BLE001 — handler errors go to caller
            return ({"id": mid, "ok": False, "etype": type(e).__name__,
                     "error": str(e)}, b"")
        result, out_arrays = out if isinstance(out, tuple) else (out, None)
        manifest, rblob = encode_arrays(out_arrays or {})
        return ({"id": mid, "ok": True, "result": result,
                 "blobs": manifest}, rblob)

    def close(self) -> None:
        self._closed_event.set()
        # a blocked accept() is NOT woken by close() from another thread
        # on Linux — shutdown() the listener first (wakes it with EINVAL),
        # with a throwaway self-connect as the portable fallback
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            socket.create_connection(self.addr, timeout=0.2).close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._accepter.join(timeout=2.0)


# -- client ------------------------------------------------------------------
class RpcClient:
    """Pooled blocking client. ``call`` checks a socket out of the pool
    (dialing a fresh one when empty), runs one request/response on it
    outside any lock, and returns it — so concurrent callers (a parked
    long-poll, a health probe, a KV stream) each get their own
    connection and never serialize behind each other.

    ``retry=RetryPolicy(...)`` arms idempotent-method retries;
    ``breaker=CircuitBreaker(...)`` arms per-peer circuit breaking;
    ``peer_host``/``local_host`` name the endpoints for the network
    fault hooks (``net_partition`` groups match against them). All
    default to off/empty — a bare client behaves exactly like ISSUE 19.
    """

    POOL_MAX = 4

    def __init__(self, addr, timeout: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 peer_host: str = "", local_host: str = "client"):
        self.addr = (str(addr[0]), int(addr[1]))
        self.timeout = float(timeout)
        self.retry = retry
        self.breaker = breaker
        self.peer_host = str(peer_host)
        self.local_host = str(local_host)
        self._lock = threading.Lock()          # guards _pool/_seq/_closed
        self._pool: list = []
        self._seq = 0
        self._closed = False
        self._call_idx = 0   # per-peer fault index (bumped only armed)

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, params: Optional[dict] = None,
             arrays: Optional[Dict[str, Any]] = None,
             timeout: Optional[float] = None,
             deadline_s: Optional[float] = None, crc: bool = False):
        """Returns ``(result, arrays)``. Raises :class:`RpcRemoteError`
        when the handler raised, :class:`RpcError` on transport death
        (the fleet-failover signal — the socket is discarded, never
        returned to the pool). With a :class:`RetryPolicy` armed,
        transport errors on idempotent methods retry with deterministic
        backoff inside the remaining ``deadline_s`` budget."""
        deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        attempt = 0
        while True:
            try:
                return self._call_once(method, params, arrays, timeout,
                                       deadline, crc)
            except RpcRemoteError:
                raise                      # transport fine; peer answered
            except RpcError as e:
                pol = self.retry
                if pol is None or not pol.retryable(method) \
                        or getattr(e, "fast", False):
                    raise                  # breaker fast-fail: no retry
                attempt += 1
                if attempt >= pol.max_attempts:
                    raise
                pause = pol.backoff(attempt - 1)
                if deadline is not None \
                        and time.monotonic() + pause >= deadline:
                    raise
                RPC_RETRIES.add(1)
                time.sleep(pause)

    def _call_once(self, method, params, arrays, timeout, deadline,
                   crc: bool):
        br = self.breaker
        if br is not None and not br.allow():
            RPC_CALLS.add()
            RPC_ERRORS.add()
            err = RpcError(f"rpc {method!r} to {self.addr[0]}:"
                           f"{self.addr[1]}: circuit breaker open")
            err.fast = True
            raise err
        drop = delay = corrupt = None
        if _FAULTS_ON[0]:
            self._call_idx += 1
            fired = _FAULTS.take_rpc(self.peer_host, method, self._call_idx)
            drop = fired.get("rpc_drop")
            delay = fired.get("rpc_delay")
            corrupt = fired.get("rpc_corrupt")
            if net_partition_blocks(self.local_host, self.peer_host):
                RPC_CALLS.add()
                RPC_ERRORS.add()
                if br is not None:
                    br.note_error()
                raise RpcError(f"rpc {method!r} to {self.addr[0]}:"
                               f"{self.addr[1]}: injected net partition "
                               f"({self.local_host}<->{self.peer_host})")
        with self._lock:
            if self._closed:
                raise RpcError("client closed")
            self._seq += 1
            mid = self._seq
            sock = self._pool.pop() if self._pool else None
        t0 = time.monotonic()
        RPC_CALLS.add()
        out = resp = None
        try:
            if drop is not None:           # injected mid-call transport
                if sock is not None:       # death, before the frame leaves
                    sock.close()
                raise ConnectionError("injected rpc_drop")
            if sock is None:
                sock = self._dial()
            manifest, blob = encode_arrays(arrays or {})
            header = {"id": mid, "method": method, "params": params or {},
                      "blobs": manifest}
            if deadline is not None:
                header["deadline_ms"] = round(
                    max(0.0, (deadline - time.monotonic()) * 1e3), 3)
            if crc:
                header["crc"] = zlib.crc32(blob)
            if delay is not None:
                header["_inject_delay_s"] = delay.secs
            frame = _pack_frame(header, blob)
            if corrupt is not None:
                jlen = len(json.dumps(header,
                                      separators=(",", ":")).encode())
                frame = _flip_byte(frame, jlen, len(blob))
            budget = self.timeout if timeout is None else timeout
            if deadline is not None:
                budget = min(budget, max(0.01, deadline - time.monotonic()))
            sock.settimeout(budget)
            sock.sendall(frame)
            resp, rblob = _recv_frame(sock)
            if resp.get("id") != mid:
                raise RpcError(
                    f"rpc {method!r}: response id {resp.get('id')} for "
                    f"request {mid} (desynced stream)")
            if resp.get("ok"):
                out = (resp.get("result"),
                       decode_arrays(resp.get("blobs"), rblob))
        except (ConnectionError, OSError, struct.error,
                json.JSONDecodeError, RpcError) as e:
            # ANY failure mid-call poisons the stream: destroy the
            # socket, never re-pool it (satellite: pool hygiene)
            RPC_ERRORS.add()
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            if br is not None:
                br.note_error()
            if isinstance(e, RpcError):
                raise
            raise RpcError(f"rpc {method!r} to {self.addr[0]}:"
                           f"{self.addr[1]}: {type(e).__name__}: {e}") from e
        # fully-validated round trip: the stream is aligned — only now
        # may the socket go back to the pool
        keep = False
        with self._lock:
            if not self._closed and len(self._pool) < self.POOL_MAX:
                self._pool.append(sock)
                keep = True
        if not keep:
            sock.close()
        RPC_CALL_MS.observe((time.monotonic() - t0) * 1e3)
        if br is not None:
            br.note_ok()
        if out is None:                    # remote handler raised/shed
            RPC_ERRORS.add()
            raise RpcRemoteError(resp.get("etype", "Exception"),
                                 resp.get("error", ""))
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pool = list(self._pool)
            self._pool.clear()
        for sock in pool:
            try:
                sock.close()
            except OSError:
                pass
