"""Thin stdlib RPC transport for the cross-host serving fleet (ISSUE 19).

One frame = ``PRPC`` magic + ``<I json_len, Q blob_len>`` + a JSON header
+ an optional binary blob. The header carries the method, scalar params,
and a manifest describing how the blob splits into named numpy arrays
(``{"name", "dtype", "shape", "nbytes"}`` each) — KV block rows ride the
blob raw, never JSON. The same frame shape serves requests and replies.

Design constraints, in order:

- **stdlib only** (socket/struct/json/threading) — the fleet must not
  grow a dependency the training side doesn't have.
- **Blocking request/response per connection.** The server runs one
  thread per connection, so a handler may legitimately block (the
  long-poll ``wait`` that streams tokens parks in ``req._cv.wait_for``
  server-side); the client keeps a small connection pool so one parked
  long-poll never delays a concurrent health probe.
- **Failure = exception, not hang.** Socket timeouts bound every call;
  a dead peer surfaces as :class:`RpcError` at the caller, which is the
  signal the fleet layer (serving/pod.py) turns into replica failover.

Threading notes (GL003/GL004): the server's connection set and the
client's socket pool are the only cross-thread state, each guarded by
its own ``_lock``; sockets are checked out under the lock but all I/O
happens outside it, so no lock is ever held across a blocking call and
no second lock is ever taken while one is held.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..monitor.stats import RPC_CALLS, RPC_CALL_MS, RPC_ERRORS

__all__ = ["RpcError", "RpcRemoteError", "RpcServer", "RpcClient",
           "encode_arrays", "decode_arrays"]

_MAGIC = b"PRPC"
_HEAD = len(_MAGIC) + 12            # magic + <I json_len> + <Q blob_len>
MAX_HEADER_BYTES = 16 * 1024 * 1024
MAX_BLOB_BYTES = 512 * 1024 * 1024


class RpcError(RuntimeError):
    """Transport-level failure: dead peer, torn frame, timeout."""


class RpcRemoteError(RpcError):
    """The remote handler raised; ``etype`` names the remote type so the
    fleet layer can distinguish e.g. a remote QueueFull from a crash."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype


# -- array codec -------------------------------------------------------------
def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes               # bfloat16/fp8 names (jax dep)

        return np.dtype(getattr(ml_dtypes, name))


def encode_arrays(arrays: Dict[str, Any]) -> Tuple[list, bytes]:
    """(manifest, blob) for a dict of numpy arrays; order-preserving."""
    manifest, parts = [], []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        raw = a.tobytes()
        manifest.append({"name": str(name), "dtype": a.dtype.name,
                         "shape": list(a.shape), "nbytes": len(raw)})
        parts.append(raw)
    return manifest, b"".join(parts)


def decode_arrays(manifest, blob: bytes) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    off = 0
    for m in manifest or ():
        n = int(m["nbytes"])
        if off + n > len(blob):
            raise RpcError(f"torn blob: manifest wants {off + n} bytes, "
                           f"frame carries {len(blob)}")
        a = np.frombuffer(blob, dtype=_np_dtype(m["dtype"]),
                          count=n // max(1, _np_dtype(m["dtype"]).itemsize),
                          offset=off)
        out[str(m["name"])] = a.reshape([int(s) for s in m["shape"]])
        off += n
    if off != len(blob):
        raise RpcError(f"torn blob: {len(blob) - off} trailing bytes")
    return out


# -- framing -----------------------------------------------------------------
def _recvall(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _send_frame(sock: socket.socket, header: dict, blob: bytes = b"") -> None:
    payload = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_MAGIC + struct.pack("<IQ", len(payload), len(blob))
                 + payload + blob)


def _recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    head = _recvall(sock, _HEAD)
    if not head.startswith(_MAGIC):
        raise RpcError(f"bad frame magic {head[:4]!r}")
    jlen, blen = struct.unpack("<IQ", head[len(_MAGIC):])
    if jlen > MAX_HEADER_BYTES or blen > MAX_BLOB_BYTES:
        raise RpcError(f"oversized frame: header {jlen}B, blob {blen}B")
    header = json.loads(_recvall(sock, jlen))
    blob = _recvall(sock, blen) if blen else b""
    return header, blob


# -- server ------------------------------------------------------------------
class RpcServer:
    """One accept thread + one thread per connection, dispatching to a
    dict of handlers ``{method: fn(params, arrays) -> result}`` where a
    handler may return either a JSON-able result or a tuple ``(result,
    arrays)`` to ship binary payloads back. Handler exceptions become
    :class:`RpcRemoteError` at the caller; they never kill the server."""

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0):
        self._handlers = dict(handlers)
        self._listener = socket.create_server((host, int(port)))
        self.addr: Tuple[str, int] = self._listener.getsockname()[:2]
        self._lock = threading.Lock()          # guards _conns
        self._conns: set = set()
        self._closed_event = threading.Event()
        self._accepter = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True)
        self._accepter.start()

    def _accept_loop(self) -> None:
        while not self._closed_event.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:                    # listener closed: shutdown
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closed_event.is_set():
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rpc-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed_event.is_set():
                try:
                    header, blob = _recv_frame(conn)
                except (ConnectionError, OSError, RpcError, ValueError):
                    break                      # peer gone / torn frame
                resp, rblob = self._dispatch(header, blob)
                try:
                    _send_frame(conn, resp, rblob)
                except (ConnectionError, OSError):
                    break
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, header: dict, blob: bytes) -> Tuple[dict, bytes]:
        mid = header.get("id")
        method = header.get("method", "")
        fn = self._handlers.get(method)
        if fn is None:
            return ({"id": mid, "ok": False, "etype": "KeyError",
                     "error": f"no such method: {method!r}"}, b"")
        try:
            arrays = decode_arrays(header.get("blobs"), blob)
            out = fn(header.get("params") or {}, arrays)
        except Exception as e:  # noqa: BLE001 — handler errors go to caller
            return ({"id": mid, "ok": False, "etype": type(e).__name__,
                     "error": str(e)}, b"")
        result, out_arrays = out if isinstance(out, tuple) else (out, None)
        manifest, rblob = encode_arrays(out_arrays or {})
        return ({"id": mid, "ok": True, "result": result,
                 "blobs": manifest}, rblob)

    def close(self) -> None:
        self._closed_event.set()
        # a blocked accept() is NOT woken by close() from another thread
        # on Linux — shutdown() the listener first (wakes it with EINVAL),
        # with a throwaway self-connect as the portable fallback
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            socket.create_connection(self.addr, timeout=0.2).close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._accepter.join(timeout=2.0)


# -- client ------------------------------------------------------------------
class RpcClient:
    """Pooled blocking client. ``call`` checks a socket out of the pool
    (dialing a fresh one when empty), runs one request/response on it
    outside any lock, and returns it — so concurrent callers (a parked
    long-poll, a health probe, a KV stream) each get their own
    connection and never serialize behind each other."""

    POOL_MAX = 4

    def __init__(self, addr, timeout: float = 30.0):
        self.addr = (str(addr[0]), int(addr[1]))
        self.timeout = float(timeout)
        self._lock = threading.Lock()          # guards _pool/_seq/_closed
        self._pool: list = []
        self._seq = 0
        self._closed = False

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, params: Optional[dict] = None,
             arrays: Optional[Dict[str, Any]] = None,
             timeout: Optional[float] = None):
        """Returns ``(result, arrays)``. Raises :class:`RpcRemoteError`
        when the handler raised, :class:`RpcError` on transport death
        (the fleet-failover signal — the socket is discarded, never
        returned to the pool)."""
        with self._lock:
            if self._closed:
                raise RpcError("client closed")
            self._seq += 1
            mid = self._seq
            sock = self._pool.pop() if self._pool else None
        t0 = time.monotonic()
        RPC_CALLS.add()
        try:
            if sock is None:
                sock = self._dial()
            manifest, blob = encode_arrays(arrays or {})
            sock.settimeout(self.timeout if timeout is None else timeout)
            _send_frame(sock, {"id": mid, "method": method,
                               "params": params or {}, "blobs": manifest},
                        blob)
            resp, rblob = _recv_frame(sock)
        except (ConnectionError, OSError, struct.error,
                json.JSONDecodeError) as e:
            RPC_ERRORS.add()
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise RpcError(f"rpc {method!r} to {self.addr[0]}:"
                           f"{self.addr[1]}: {type(e).__name__}: {e}") from e
        keep = False
        with self._lock:
            if not self._closed and len(self._pool) < self.POOL_MAX:
                self._pool.append(sock)
                keep = True
        if not keep:
            sock.close()
        RPC_CALL_MS.observe((time.monotonic() - t0) * 1e3)
        if resp.get("id") != mid:
            RPC_ERRORS.add()
            raise RpcError(f"rpc {method!r}: response id {resp.get('id')} "
                           f"for request {mid} (desynced stream)")
        if not resp.get("ok"):
            RPC_ERRORS.add()
            raise RpcRemoteError(resp.get("etype", "Exception"),
                                 resp.get("error", ""))
        return resp.get("result"), decode_arrays(resp.get("blobs"), rblob)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pool = list(self._pool)
            self._pool.clear()
        for sock in pool:
            try:
                sock.close()
            except OSError:
                pass
