"""Structured (constrained) decoding — JSON-schema / regex token masks
(ISSUE 11).

A constraint compiles to a byte-level DFA; the vocabulary is projected
onto it per state: token t is allowed in DFA state s iff walking t's
byte expansion from s never leaves the automaton. The resulting (V,)
bool mask rides into the engine's jitted sampling program as a plain
array input (serving.sampling ``mask=``), so a masked row composes with
temperature/top-k/top-p exactly — the filter chain renormalizes over
the allowed set. The automaton itself advances HOST-side, one token per
emitted token, at request granularity (the same host/device split as
the block tables: per-token control state stays out of the compiled
step).

Layers:

- :func:`compile_regex` — a self-contained regex subset (literals,
  escapes, ``.``, character classes with ranges/negation, groups,
  alternation, ``* + ?`` and ``{m,n}``) → Thompson NFA → subset-
  construction DFA over bytes. No ``re`` involvement: ``re`` can only
  test complete strings, while masking needs PREFIX-liveness per state.
- :func:`schema_to_regex` — a practical JSON-schema subset (object with
  fixed ``properties`` (order = emission order), ``string``/
  ``integer``/``number``/``boolean``/``null``, ``enum``, nested
  objects, ``array`` with ``items``/``minItems``/``maxItems``) → a
  regex for the canonical compact serialization. ``json.loads`` of a
  completed match always succeeds and validates against the schema.
- :class:`TokenConstraint` — the shareable compiled artifact: DFA +
  per-(state) token-mask cache over a tokenizer's id→bytes table.
  :meth:`cursor` mints the per-request mutable state the engine holds
  (:class:`ConstraintCursor`: ``mask()`` / ``advance(tok)`` /
  ``finished``).

EOS is allowed exactly in ACCEPTING states; a cursor whose state
accepts and has no live continuation reports ``finished`` and the
engine stops the stream (finish_reason ``"stop"``) — so a constrained
request terminates when its JSON object closes even if the model would
happily keep going.
"""
from __future__ import annotations

import json
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = ["compile_regex", "schema_to_regex", "compile_constraint",
           "TokenConstraint", "ConstraintCursor", "Dfa"]


# ==========================================================================
# regex subset -> NFA (Thompson construction)
# ==========================================================================

_EPS = -1          # epsilon edge label
_ANY = -2          # "." — any byte except newline


class _Nfa:
    """Fragment with one start and one accept state; edges are
    (label, dst) lists where label is a frozenset of bytes, _EPS."""

    def __init__(self):
        self.edges: List[List[Tuple[object, int]]] = []

    def state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1


class _RegexParser:
    """Recursive-descent parser for the supported subset."""

    def __init__(self, pattern: str):
        self.pat = pattern
        self.i = 0
        self.nfa = _Nfa()

    def parse(self) -> Tuple[int, int]:
        start, accept = self._alternation()
        if self.i != len(self.pat):
            raise ValueError(
                f"regex: unexpected {self.pat[self.i]!r} at {self.i} "
                f"in {self.pat!r}")
        return start, accept

    # alternation := concat ('|' concat)*
    def _alternation(self) -> Tuple[int, int]:
        frags = [self._concat()]
        while self._peek() == "|":
            self.i += 1
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s, a = self.nfa.state(), self.nfa.state()
        for fs, fa in frags:
            self.nfa.edges[s].append((_EPS, fs))
            self.nfa.edges[fa].append((_EPS, a))
        return s, a

    def _concat(self) -> Tuple[int, int]:
        frags = []
        while self._peek() not in ("", "|", ")"):
            frags.append(self._quantified())
        if not frags:
            s = self.nfa.state()
            return s, s
        for (_, a1), (s2, _) in zip(frags, frags[1:]):
            self.nfa.edges[a1].append((_EPS, s2))
        return frags[0][0], frags[-1][1]

    def _quantified(self) -> Tuple[int, int]:
        frag = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self.i += 1
                frag = self._star(frag)
            elif c == "+":
                self.i += 1
                s2, a2 = self._copy(frag)
                star = self._star((s2, a2))
                self.nfa.edges[frag[1]].append((_EPS, star[0]))
                frag = (frag[0], star[1])
            elif c == "?":
                self.i += 1
                self.nfa.edges[frag[0]].append((_EPS, frag[1]))
            elif c == "{":
                frag = self._repeat(frag)
            else:
                return frag

    def _star(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        fs, fa = frag
        s, a = self.nfa.state(), self.nfa.state()
        self.nfa.edges[s] += [(_EPS, fs), (_EPS, a)]
        self.nfa.edges[fa] += [(_EPS, fs), (_EPS, a)]
        return s, a

    def _repeat(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        j = self.pat.index("}", self.i)
        spec = self.pat[self.i + 1:j]
        self.i = j + 1
        lo, _, hi = spec.partition(",")
        m = int(lo)
        n = m if not _ else (int(hi) if hi else None)
        if n is not None and (n < m or n > 256):
            raise ValueError(f"regex: bad repeat {{{spec}}}")
        # expand: m mandatory copies, then (n-m) optional (or a star)
        s = a = None
        for _k in range(m):
            fs, fa = self._copy(frag)
            if s is None:
                s, a = fs, fa
            else:
                self.nfa.edges[a].append((_EPS, fs))
                a = fa
        if n is None:
            tail = self._star(self._copy(frag))
        else:
            tail = None
            for _k in range(n - m):
                fs, fa = self._copy(frag)
                self.nfa.edges[fs].append((_EPS, fa))   # optional
                if tail is None:
                    tail = (fs, fa)
                else:
                    self.nfa.edges[tail[1]].append((_EPS, fs))
                    tail = (tail[0], fa)
        if tail is not None:
            if s is None:
                s, a = tail
            else:
                self.nfa.edges[a].append((_EPS, tail[0]))
                a = tail[1]
        if s is None:           # {0} / {0,0}
            s = a = self.nfa.state()
        return s, a

    def _copy(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        """Deep-copy a fragment's reachable subgraph (quantifier
        expansion needs independent copies)."""
        fs, fa = frag
        seen: Dict[int, int] = {}
        stack = [fs]
        seen[fs] = self.nfa.state()
        while stack:
            old = stack.pop()
            for label, dst in list(self.nfa.edges[old]):
                if dst not in seen:
                    seen[dst] = self.nfa.state()
                    stack.append(dst)
                self.nfa.edges[seen[old]].append((label, seen[dst]))
        if fa not in seen:      # accept unreachable from start: isolated
            seen[fa] = self.nfa.state()
        return seen[fs], seen[fa]

    # atoms
    def _peek(self) -> str:
        return self.pat[self.i] if self.i < len(self.pat) else ""

    _ESCAPES = {"n": b"\n", "r": b"\r", "t": b"\t", "0": b"\0"}
    _CLASSES = {
        "d": frozenset(range(0x30, 0x3A)),
        "w": frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
                       + list(range(0x61, 0x7B)) + [0x5F]),
        "s": frozenset(b" \t\r\n\f\v"),
    }

    def _atom(self) -> Tuple[int, int]:
        c = self._peek()
        if c == "(":
            self.i += 1
            frag = self._alternation()
            if self._peek() != ")":
                raise ValueError(f"regex: unbalanced '(' in {self.pat!r}")
            self.i += 1
            return frag
        if c == "[":
            return self._edge(self._char_class())
        if c == ".":
            self.i += 1
            return self._edge(frozenset(set(range(256)) - {0x0A}))
        if c == "\\":
            self.i += 1
            e = self._peek()
            self.i += 1
            if e in self._CLASSES:
                return self._edge(self._CLASSES[e])
            if e.upper() in self._CLASSES:   # \D \W \S
                return self._edge(
                    frozenset(set(range(256)) - self._CLASSES[e.lower()]))
            if e in self._ESCAPES:
                return self._edge(frozenset(self._ESCAPES[e]))
            if e == "x":
                byte = int(self.pat[self.i:self.i + 2], 16)
                self.i += 2
                return self._edge(frozenset({byte}))
            return self._edge(frozenset(e.encode("utf-8")) if len(
                e.encode("utf-8")) == 1 else None, literal=e)
        if c in ("*", "+", "?", "{", "}"):
            raise ValueError(f"regex: dangling {c!r} at {self.i}")
        self.i += 1
        return self._literal(c)

    def _literal(self, ch: str) -> Tuple[int, int]:
        data = ch.encode("utf-8")
        s = self.nfa.state()
        cur = s
        for b in data:
            nxt = self.nfa.state()
            self.nfa.edges[cur].append((frozenset({b}), nxt))
            cur = nxt
        return s, cur

    def _edge(self, byte_set, literal: Optional[str] = None):
        if byte_set is None:          # multi-byte escaped literal
            return self._literal(literal)
        s, a = self.nfa.state(), self.nfa.state()
        self.nfa.edges[s].append((byte_set, a))
        return s, a

    def _class_one(self):
        """One class member: ("class", byte_set) for shorthand escapes,
        ("chr", code_point) otherwise — shared by both ends of a
        range so ``[\\x00-\\x1f]`` parses."""
        c = self._peek()
        if c == "\\":
            self.i += 1
            e = self._peek()
            self.i += 1
            if e in self._CLASSES:
                return ("class", self._CLASSES[e])
            if e in self._ESCAPES:
                return ("chr", self._ESCAPES[e][0])
            if e == "x":
                v = int(self.pat[self.i:self.i + 2], 16)
                self.i += 2
                return ("chr", v)
            return ("chr", ord(e))
        self.i += 1
        return ("chr", ord(c))

    def _char_class(self) -> FrozenSet[int]:
        assert self._peek() == "["
        self.i += 1
        negate = self._peek() == "^"
        if negate:
            self.i += 1
        out: Set[int] = set()
        first = True
        while True:
            c = self._peek()
            if c == "":
                raise ValueError(f"regex: unbalanced '[' in {self.pat!r}")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            kind, val = self._class_one()
            if kind == "class":
                out |= val
                continue
            if self._peek() == "-" and self.i + 1 < len(self.pat) \
                    and self.pat[self.i + 1] != "]":
                self.i += 1
                k2, hi = self._class_one()
                if k2 == "class":
                    raise ValueError(
                        f"regex: class shorthand as range bound in "
                        f"{self.pat!r}")
                out |= set(range(val, hi + 1))
            else:
                out.add(val)
        if any(b > 255 for b in out):
            raise ValueError("regex: non-byte characters in class "
                             "(escape multibyte chars outside [])")
        return frozenset(set(range(256)) - out) if negate else frozenset(out)


# ==========================================================================
# NFA -> DFA (subset construction over bytes)
# ==========================================================================

class Dfa:
    """Byte-level DFA: ``trans[state]`` maps byte -> state;
    ``accepting`` is the set of match states. Every state is live
    (some path reaches an accepting state) — dead subsets are pruned at
    construction, so "no transition" already means "this byte kills the
    match"."""

    __slots__ = ("trans", "accepting", "start")

    def __init__(self, trans: List[Dict[int, int]], accepting: Set[int],
                 start: int):
        self.trans = trans
        self.accepting = accepting
        self.start = start

    @property
    def n_states(self) -> int:
        return len(self.trans)

    def matches(self, data: bytes) -> bool:
        s = self.start
        for b in data:
            s = self.trans[s].get(b)
            if s is None:
                return False
        return s in self.accepting


def _eps_closure(nfa: _Nfa, states: Set[int]) -> FrozenSet[int]:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for label, dst in nfa.edges[s]:
            if label == _EPS and dst not in out:
                out.add(dst)
                stack.append(dst)
    return frozenset(out)


def compile_regex(pattern: str, max_states: int = 4096) -> Dfa:
    """Compile the supported regex subset to a pruned byte DFA (full
    anchored match — the constraint is the WHOLE generated string)."""
    parser = _RegexParser(pattern)
    start, accept = parser.parse()
    nfa = parser.nfa
    d0 = _eps_closure(nfa, {start})
    ids: Dict[FrozenSet[int], int] = {d0: 0}
    trans: List[Dict[int, int]] = [{}]
    accepting: Set[int] = set()
    work = [d0]
    while work:
        cur = work.pop()
        ci = ids[cur]
        if accept in cur:
            accepting.add(ci)
        # group reachable byte edges
        by_byte: Dict[int, Set[int]] = {}
        for s in cur:
            for label, dst in nfa.edges[s]:
                if label == _EPS:
                    continue
                for b in label:
                    by_byte.setdefault(b, set()).add(dst)
        for b, dsts in by_byte.items():
            nxt = _eps_closure(nfa, dsts)
            if nxt not in ids:
                if len(ids) >= max_states:
                    raise ValueError(
                        f"regex {pattern!r}: DFA exceeds {max_states} states")
                ids[nxt] = len(ids)
                trans.append({})
                work.append(nxt)
            trans[ci][b] = ids[nxt]
    # prune dead states (no path to accepting): masking needs PREFIX
    # liveness, so "has a transition" must imply "can still match"
    alive: Set[int] = set(accepting)
    changed = True
    while changed:
        changed = False
        for i, edges in enumerate(trans):
            if i not in alive and any(d in alive for d in edges.values()):
                alive.add(i)
                changed = True
    if 0 not in alive:
        raise ValueError(f"regex {pattern!r} matches nothing")
    remap = {old: new for new, old in enumerate(sorted(alive))}
    pruned = [{b: remap[d] for b, d in trans[old].items() if d in alive}
              for old in sorted(alive)]
    return Dfa(pruned, {remap[a] for a in accepting if a in alive}, remap[0])


# ==========================================================================
# JSON schema -> regex (canonical compact serialization)
# ==========================================================================

_JSON_STR = r'"[^"\\\x00-\x1f]*"'
_JSON_INT = r"-?(0|[1-9][0-9]*)"
_JSON_NUM = _JSON_INT + r"(\.[0-9]+)?"


def _escape_literal(text: str) -> str:
    out = []
    for ch in text:
        if ch in r".[]{}()*+?|\^$-":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def schema_to_regex(schema: dict, depth: int = 0) -> str:
    """JSON-schema subset → regex of the canonical COMPACT serialization
    (no whitespace, object keys in ``properties`` order, every listed
    property required). Completed matches json.loads cleanly and
    satisfy the schema's types."""
    if depth > 16:
        raise ValueError("json schema nests deeper than 16 levels")
    if "enum" in schema:
        alts = []
        for v in schema["enum"]:
            alts.append(_escape_literal(json.dumps(v, separators=(",", ":"))))
        return "(" + "|".join(alts) + ")"
    t = schema.get("type")
    if t == "string":
        pat = schema.get("pattern")
        if pat is not None:
            return '"' + pat + '"'
        lo = int(schema.get("minLength", 0))
        hi = schema.get("maxLength")
        body = r'[^"\\\x00-\x1f]'
        rep = f"{{{lo},{int(hi)}}}" if hi is not None else \
            (f"{{{lo},}}" if lo else "*")
        return '"' + body + rep + '"'
    if t == "integer":
        return _JSON_INT
    if t == "number":
        return _JSON_NUM
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "integer"}),
                               depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if lo == 0:
            inner = f"({item}(,{item})*)?" if hi is None else \
                f"({item}(,{item}){{0,{int(hi) - 1}}})?"
        else:
            more = f"(,{item})*" if hi is None else \
                f"(,{item}){{{lo - 1},{int(hi) - 1}}}"
            inner = item + more
        return r"\[" + inner + r"\]"
    if t == "object" or "properties" in schema:
        props = schema.get("properties", {})
        if not props:
            return r"\{\}"
        parts = []
        for name, sub in props.items():
            parts.append('"' + _escape_literal(name) + '":'
                         + schema_to_regex(sub, depth + 1))
        return r"\{" + ",".join(parts) + r"\}"
    raise ValueError(f"unsupported json schema: {schema!r}")


# ==========================================================================
# token projection
# ==========================================================================

class TokenConstraint:
    """A compiled constraint shared across requests: the byte DFA plus a
    lazily-built per-state token mask over one vocabulary.

    ``token_table`` maps token id -> byte expansion (None for specials
    — always masked out except EOS, which is allowed in accepting
    states). Build one per (constraint, tokenizer) pair and mint a
    :class:`cursor` per request."""

    def __init__(self, dfa: Dfa, token_table: Sequence[Optional[bytes]],
                 eos_id: Optional[int] = None):
        self.dfa = dfa
        self.token_table = list(token_table)
        self.vocab_size = len(self.token_table)
        self.eos_id = eos_id
        self._masks: Dict[int, np.ndarray] = {}
        self._steps: Dict[Tuple[int, int], Optional[int]] = {}

    @classmethod
    def from_tokenizer(cls, dfa: Dfa, tokenizer, vocab_size: Optional[int]
                       = None) -> "TokenConstraint":
        """Project a ByteTokenizer-shaped vocabulary (``token_bytes`` +
        ``eos_id``) onto the DFA. ``vocab_size`` pads the mask out to
        the MODEL's vocab (ids past the tokenizer are never allowed)."""
        n = vocab_size if vocab_size is not None else tokenizer.vocab_size
        table = [tokenizer.token_bytes(t) if t < tokenizer.vocab_size
                 else None for t in range(n)]
        return cls(dfa, table, eos_id=tokenizer.eos_id)

    def _walk(self, state: int, data: bytes) -> Optional[int]:
        s = state
        for b in data:
            s = self.dfa.trans[s].get(b)
            if s is None:
                return None
        return s

    def step(self, state: int, tok: int) -> Optional[int]:
        """DFA state after emitting ``tok`` (None = dead/disallowed)."""
        key = (state, int(tok))
        hit = self._steps.get(key, False)
        if hit is not False:
            return hit
        data = self.token_table[int(tok)] if 0 <= tok < self.vocab_size \
            else None
        nxt = self._walk(state, data) if data is not None else None
        self._steps[key] = nxt
        return nxt

    def mask(self, state: int) -> np.ndarray:
        """(V,) bool: tokens whose byte expansion keeps the DFA alive
        from ``state``; EOS allowed iff ``state`` accepts."""
        m = self._masks.get(state)
        if m is None:
            m = np.zeros(self.vocab_size, bool)
            for t, data in enumerate(self.token_table):
                if data is not None and self._walk(state, data) is not None:
                    m[t] = True
            m.setflags(write=False)
            self._masks[state] = m
        if self.eos_id is not None and state in self.dfa.accepting:
            out = m.copy()
            out[self.eos_id] = True
            return out
        return m

    def cursor(self) -> "ConstraintCursor":
        return ConstraintCursor(self)


class ConstraintCursor:
    """Per-request automaton state the engine advances token by token.
    Owned by the scheduler thread; not thread-safe by design."""

    __slots__ = ("constraint", "state", "dead")

    def __init__(self, constraint: TokenConstraint):
        self.constraint = constraint
        self.state: int = constraint.dfa.start
        self.dead = False

    def mask(self) -> np.ndarray:
        return self.constraint.mask(self.state)

    def advance(self, tok: int) -> bool:
        """Consume one emitted token; False when it killed the match
        (possible only for tokens the mask never offered — EOS, or an
        unmasked escape-hatch path)."""
        if self.dead:
            return False
        if tok == self.constraint.eos_id:
            return self.state in self.constraint.dfa.accepting
        nxt = self.constraint.step(self.state, int(tok))
        if nxt is None:
            self.dead = True
            return False
        self.state = nxt
        return True

    @property
    def accepting(self) -> bool:
        return not self.dead and self.state in self.constraint.dfa.accepting

    @property
    def finished(self) -> bool:
        """Accepting with no live continuation — generation is complete
        (the engine evicts with finish_reason "stop")."""
        return self.accepting and not self.constraint.dfa.trans[self.state]


def compile_constraint(tokenizer=None, regex: Optional[str] = None,
                       json_schema: Optional[dict] = None,
                       vocab_size: Optional[int] = None) -> TokenConstraint:
    """One-stop constructor: exactly one of ``regex`` / ``json_schema``
    plus a tokenizer (ByteTokenizer surface) → a shareable
    :class:`TokenConstraint`."""
    if (regex is None) == (json_schema is None):
        raise ValueError("pass exactly one of regex / json_schema")
    if tokenizer is None:
        raise ValueError("compile_constraint needs the engine's tokenizer "
                         "(token ids must map to bytes)")
    pattern = regex if regex is not None else schema_to_regex(json_schema)
    return TokenConstraint.from_tokenizer(compile_regex(pattern), tokenizer,
                                          vocab_size=vocab_size)
