"""Continuous-batching inference engine (Orca-style) over the KV cache.

Request lifecycle::

    submit() ──► bounded queue ──► [admit: prefill into a free slot]
                                        │
        stream()/result() ◄── tokens ◄──┤  one jitted decode step per tick,
                                        │  batched over ALL occupied slots
                  [evict: eos / max_tokens / deadline / cancel / capacity]

A single scheduler thread owns the device state (params, cache buffers,
jit calls); ``submit`` may be called from any thread and only touches the
queue. Each tick the scheduler (1) admits waiting requests into free
slots — prefill-and-insert, one sequence at a time, streaming the first
token — and (2) runs ONE compiled decode step over the whole slot batch,
so a late arrival starts generating next tick without draining anyone
(the reference's AnalysisPredictor has no such path; batching there is
caller-side). Finished sequences release their slot between ticks; the
batch never stalls on the longest request.

Jit surface: exactly two programs in steady state — a decode step at the
fixed (n_slots,) batch shape, and a prefill per prompt-length bucket
(prompts are end-padded to the next power of two, which causality makes
exact). Cache buffers are donated through both, so serving allocates
nothing per token. ``FLAGS_serving_jit=0`` swaps in an un-jitted
full-recompute reference decode (same scheduler, same sampling) as the
numerics escape hatch.

Observability: gauges serving_queue_depth / serving_slot_occupancy /
serving_prefill_ms / serving_decode_ms / serving_tokens_per_s /
serving_evictions, plus ``serving.prefill`` / ``serving.decode_step``
trace spans that ``tools/trace_report.py`` turns into a prefill-vs-decode
verdict.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import native
from ..models.gpt import gpt_decode_step, gpt_forward, gpt_prefill
from ..monitor.stats import (SERVING_DECODE_MS, SERVING_EVICTIONS,
                             SERVING_PREFILL_MS, SERVING_QUEUE_DEPTH,
                             SERVING_SLOT_OCCUPANCY, SERVING_TOKENS_PER_S)
from ..monitor.trace import span
from .kv_cache import KVCache, cache_insert
from .sampling import sample_tokens

__all__ = ["InferenceEngine", "GenerationRequest", "QueueFull"]


class QueueFull(RuntimeError):
    """submit() backpressure: the bounded request queue is at capacity."""


# finish reasons
EOS = "eos"
LENGTH = "length"
DEADLINE = "deadline"
CANCELLED = "cancelled"
SHUTDOWN = "shutdown"
ERROR = "error"


class GenerationRequest:
    """Per-request future returned by :meth:`InferenceEngine.submit`.

    Tokens stream in as the scheduler generates them: ``stream()`` yields
    them live, ``result()`` blocks for the full list, ``finish_reason``
    says why generation stopped (eos/length/deadline/cancelled/shutdown).
    """

    def __init__(self, prompt, max_new_tokens: int, temperature: float,
                 top_k: int, top_p: float, eos_id: Optional[int],
                 deadline: Optional[float]):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_id = eos_id
        self.deadline = deadline          # absolute time.monotonic() or None
        self.tokens: List[int] = []       # generated ids (includes eos)
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._cancelled = False
        self._cv = threading.Condition()

    # -- scheduler side ------------------------------------------------------
    def _push(self, tok: int) -> None:
        with self._cv:
            self.tokens.append(tok)
            self._cv.notify_all()

    def _finish(self, reason: str, error: Optional[BaseException] = None):
        with self._cv:
            if self.finish_reason is None:
                self.finish_reason = reason
                self.error = error
            self._cv.notify_all()

    # -- user side -----------------------------------------------------------
    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    def cancel(self) -> None:
        """Ask the scheduler to drop this request at its next tick (or at
        admission, if still queued)."""
        self._cancelled = True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until generation stops; returns the generated ids (the
        tokens produced before an eviction are kept — a deadline/cancel
        result is the partial sequence)."""
        with self._cv:
            if not self._cv.wait_for(lambda: self.finish_reason is not None,
                                     timeout):
                raise TimeoutError("generation still in progress")
        if self.error is not None:
            raise RuntimeError("generation failed") from self.error
        return list(self.tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as they are generated; returns when finished."""
        i = 0
        while True:
            with self._cv:
                if not self._cv.wait_for(
                        lambda: len(self.tokens) > i
                        or self.finish_reason is not None, timeout):
                    raise TimeoutError("generation still in progress")
                fresh = self.tokens[i:]
                finished = self.finish_reason is not None
            for t in fresh:
                yield t
            i += len(fresh)
            if finished and i >= len(self.tokens):
                if self.error is not None:
                    raise RuntimeError("generation failed") from self.error
                return


class _Slot:
    """Host-side state of one occupied cache slot."""

    __slots__ = ("req", "length", "last_token", "generated")

    def __init__(self, req: GenerationRequest, length: int, last_token: int):
        self.req = req
        self.length = length          # tokens whose K/V are in the cache
        self.last_token = last_token  # input of the next decode step
        self.generated = 1            # prefill already streamed one token


class InferenceEngine:
    """Continuous-batching generation server for a functional GPT model.

    ::

        eng = InferenceEngine(cfg, params, n_slots=8)
        req = eng.submit(prompt_ids, max_new_tokens=64, temperature=0.8)
        for tok in req.stream(): ...
        eng.shutdown()

    ``params`` is a gpt_init-layout pytree (flat blocks — stage-stacked
    training layouts must be unstacked first).

    ``int8_weights=True`` quantizes the block matmul weights to int8
    per-channel (models.gpt.quantize_gpt_weights) for the DECODE step —
    the steady-state batched tick runs through the Pallas fused int8
    matmul (ops/int8_matmul.py; dequant in the kernel epilogue, int8 at
    2x the bf16 MXU rate on v5e). Prefill and the FLAGS_serving_jit=0
    reference decode keep the fp weights, so admission numerics are
    unchanged; decode tokens are near-greedy-identical but not pinned
    bit-for-bit (weight rounding). Default off.
    """

    def __init__(self, cfg, params, n_slots: int = 4,
                 max_len: Optional[int] = None, queue_size: int = 64,
                 eos_id: Optional[int] = None, seed: int = 0,
                 int8_weights: bool = False):
        self.cfg = cfg
        self._params = jax.device_put(params)
        self.int8_weights = bool(int8_weights)
        if int8_weights:
            from ..models.gpt import quantize_gpt_weights
            from ..monitor.stats import INT8_MATMUL_CALLS

            self._decode_params = jax.device_put(
                quantize_gpt_weights(params))
            INT8_MATMUL_CALLS.add()
        else:
            self._decode_params = self._params
        self.cache = KVCache(cfg, n_slots, max_len)
        self.n_slots = self.cache.n_slots
        self.max_len = self.cache.max_len
        self.eos_id = eos_id
        self._queue: collections.deque = collections.deque()
        self._queue_size = int(queue_size)
        self._cv = threading.Condition()
        self._slots: List[Optional[_Slot]] = [None] * self.n_slots
        self._stop = False
        self._drain = True
        self._error: Optional[BaseException] = None  # scheduler crash cause
        self._base_key = jax.random.key(seed)
        self._tick = 0
        # float running totals behind the int ms gauges (prefetch.py idiom:
        # sub-ms ticks still accumulate)
        self._prefill_ms = 0.0
        self._decode_ms = 0.0
        self._window: collections.deque = collections.deque()  # (t, n_tokens)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(1, 2))
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(1, 2))
        self._thread = threading.Thread(target=self._run,
                                        name="serving-scheduler", daemon=True)
        self._thread.start()

    # -- compiled programs ---------------------------------------------------
    def _decode_fn(self, params, k, v, positions, tokens, key, temps,
                   top_ks, top_ps):
        logits, (k, v) = gpt_decode_step(self.cfg, params, (k, v),
                                         positions, tokens)
        toks = sample_tokens(logits, key, temps, top_ks, top_ps)
        return toks, k, v

    def _prefill_fn(self, params, k, v, tokens, slot, true_len, key, temp,
                    top_k, top_p):
        # tokens (1, S_pad) end-padded; causality keeps positions < true_len
        # exact, and the logits/cache rows past true_len are never read
        logits, (ke, ve) = gpt_prefill(self.cfg, params, tokens)
        k, v = cache_insert(k, v, slot, ke[0], ve[0])
        last = jax.lax.dynamic_index_in_dim(logits[0], true_len - 1, 0,
                                            keepdims=False)
        tok = sample_tokens(last[None], key, temp[None], top_k[None],
                            top_p[None])[0]
        return tok, k, v

    # -- public API ----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_id: Optional[int] = None, deadline_s: Optional[float] = None,
               block: bool = True,
               timeout: Optional[float] = None) -> GenerationRequest:
        """Queue a generation request; returns its streaming handle.

        Backpressure: when the bounded queue is full, ``block=True`` waits
        (up to ``timeout`` seconds) for space and raises :class:`QueueFull`
        on timeout; ``block=False`` raises immediately. ``deadline_s`` is a
        wall-clock budget from now — a request over budget is evicted with
        ``finish_reason="deadline"`` wherever it is (queued or mid-decode).
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size >= self.max_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to generate "
                f"(cache max_len={self.max_len})")
        req = GenerationRequest(
            prompt, max_new_tokens, temperature, top_k, top_p,
            self.eos_id if eos_id is None else eos_id,
            None if deadline_s is None else time.monotonic() + deadline_s)
        with self._cv:
            self._check_open()
            if len(self._queue) >= self._queue_size:
                if not block:
                    raise QueueFull(
                        f"serving queue at capacity ({self._queue_size})")
                ok = self._cv.wait_for(
                    lambda: self._stop
                    or len(self._queue) < self._queue_size, timeout)
                if not ok:
                    raise QueueFull(
                        f"serving queue still full after {timeout}s")
                self._check_open()
            self._queue.append(req)
            SERVING_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()
        return req

    def generate(self, prompt: Sequence[int], **kw) -> List[int]:
        """Blocking convenience wrapper: submit + result."""
        return self.submit(prompt, **kw).result()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the scheduler. ``drain=True`` finishes every submitted
        request first; ``drain=False`` evicts them with
        ``finish_reason="shutdown"``."""
        with self._cv:
            self._stop = True
            self._drain = drain
            self._cv.notify_all()
        self._thread.join(timeout)

    @property
    def occupancy(self) -> int:
        return self.cache.occupancy

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- scheduler -----------------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    busy = bool(self._queue) or any(
                        s is not None for s in self._slots)
                    if self._stop and (not self._drain or not busy):
                        break
                    if not busy:
                        self._cv.wait(0.05)
                        continue
                self._admit()
                if any(s is not None for s in self._slots):
                    self._decode_tick()
        except BaseException as e:  # noqa: BLE001 — fail every request, not silently
            self._abort(e)
        finally:
            with self._cv:
                self._stop = True
                leftovers = list(self._queue)
                self._queue.clear()
                SERVING_QUEUE_DEPTH.set(0)
                self._cv.notify_all()
            for req in leftovers:
                req._finish(SHUTDOWN)
            for s, st in enumerate(self._slots):
                if st is not None:
                    self._evict(s, SHUTDOWN)

    def _check_open(self) -> None:
        """Fail fast once the scheduler is gone: nothing will ever drain
        the queue again, so enqueueing would hang the caller forever.
        After a crash the stored cause rides the error so callers see WHY
        the engine died, not just that it is closed."""
        if not self._stop:
            return
        if self._error is not None:
            raise RuntimeError(
                f"InferenceEngine scheduler crashed: "
                f"{type(self._error).__name__}: {self._error}") \
                from self._error
        raise RuntimeError("InferenceEngine is shut down")

    def _abort(self, err: BaseException) -> None:
        with self._cv:
            # close the engine BEFORE failing requests so a racing
            # submit() cannot slip into the dead queue
            self._error = err
            self._stop = True
            leftovers = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        for s, st in enumerate(self._slots):
            if st is not None:
                st.req._finish(ERROR, err)
        for req in leftovers:
            req._finish(ERROR, err)

    def _admit(self) -> None:
        """Move queued requests into free slots (prefill-and-insert)."""
        while self.cache.free_count > 0:
            with self._cv:
                if not self._queue:
                    break
                req = self._queue.popleft()
                SERVING_QUEUE_DEPTH.set(len(self._queue))
                self._cv.notify_all()   # wake submitters blocked on full
            if req._cancelled:
                req._finish(CANCELLED)
                continue
            if req.deadline is not None and time.monotonic() > req.deadline:
                req._finish(DEADLINE)
                continue
            slot = self.cache.alloc()
            try:
                self._prefill(req, slot)
            except BaseException as e:  # noqa: BLE001
                # mid-admission crash: the request is in neither the
                # queue nor a slot, so _abort would miss it — fail it
                # here before the scheduler unwinds
                if self._slots[slot] is None:
                    self.cache.release(slot)
                req._finish(ERROR, e)
                raise
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _next_key(self):
        key = jax.random.fold_in(self._base_key, self._tick)
        self._tick += 1
        return key

    def _prefill(self, req: GenerationRequest, slot: int) -> None:
        S = int(req.prompt.size)
        t0 = time.perf_counter()
        with span("serving.prefill", cat="serving",
                  args={"slot": slot, "prompt_len": S}):
            if native.serving_jit[0]:
                s_pad = self._bucket(S)
                toks = np.zeros((1, s_pad), np.int32)
                toks[0, :S] = req.prompt
                tok, self.cache.k, self.cache.v = self._prefill_jit(
                    self._params, self.cache.k, self.cache.v,
                    jnp.asarray(toks), np.int32(slot), np.int32(S),
                    self._next_key(), np.float32(req.temperature),
                    np.int32(req.top_k), np.float32(req.top_p))
            else:
                logits = gpt_forward(self.cfg, self._params,
                                     jnp.asarray(req.prompt[None]))
                tok = sample_tokens(
                    logits[:, -1], self._next_key(),
                    jnp.float32(req.temperature)[None],
                    jnp.int32(req.top_k)[None],
                    jnp.float32(req.top_p)[None])[0]
            tok = int(tok)
        self._note_ms(SERVING_PREFILL_MS, "_prefill_ms",
                      (time.perf_counter() - t0) * 1e3)
        st = _Slot(req, length=S, last_token=tok)
        self._slots[slot] = st
        self.cache.lengths[slot] = S
        req._push(tok)
        self._note_tokens(1)
        reason = self._finish_reason(st, tok)
        if reason is not None:
            self._evict(slot, reason)

    def _decode_tick(self) -> None:
        now = time.monotonic()
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req._cancelled:
                self._evict(s, CANCELLED)
            elif st.req.deadline is not None and now > st.req.deadline:
                self._evict(s, DEADLINE)
        active = [s for s in range(self.n_slots) if self._slots[s] is not None]
        if not active:
            return

        positions = np.zeros(self.n_slots, np.int32)
        tokens = np.zeros(self.n_slots, np.int32)
        temps = np.zeros(self.n_slots, np.float32)
        top_ks = np.zeros(self.n_slots, np.int32)
        top_ps = np.ones(self.n_slots, np.float32)
        for s in active:
            st = self._slots[s]
            positions[s] = st.length
            tokens[s] = st.last_token
            temps[s] = st.req.temperature
            top_ks[s] = st.req.top_k
            top_ps[s] = st.req.top_p

        t0 = time.perf_counter()
        with span("serving.decode_step", cat="serving",
                  args={"batch": len(active)}):
            if native.serving_jit[0]:
                out, self.cache.k, self.cache.v = self._decode_jit(
                    self._decode_params, self.cache.k, self.cache.v,
                    positions,
                    tokens, self._next_key(), temps, top_ks, top_ps)
                out = np.asarray(out)
            else:
                # reference decode: full recompute per sequence, no cache
                out = np.zeros(self.n_slots, np.int32)
                key = self._next_key()
                for s in active:
                    st = self._slots[s]
                    seq = np.concatenate(
                        [st.req.prompt, np.asarray(st.req.tokens, np.int32)])
                    logits = gpt_forward(self.cfg, self._params,
                                         jnp.asarray(seq[None]))
                    out[s] = int(sample_tokens(
                        logits[:, -1], jax.random.fold_in(key, s),
                        temps[s:s + 1], top_ks[s:s + 1], top_ps[s:s + 1])[0])
        self._note_ms(SERVING_DECODE_MS, "_decode_ms",
                      (time.perf_counter() - t0) * 1e3)

        for s in active:
            st = self._slots[s]
            tok = int(out[s])
            st.length += 1
            st.generated += 1
            st.last_token = tok
            self.cache.lengths[s] = st.length
            st.req._push(tok)
            reason = self._finish_reason(st, tok)
            if reason is not None:
                self._evict(s, reason)
        self._note_tokens(len(active))
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)

    def _finish_reason(self, st: _Slot, tok: int) -> Optional[str]:
        if st.req.eos_id is not None and tok == st.req.eos_id:
            return EOS
        if st.generated >= st.req.max_new_tokens:
            return LENGTH
        if st.length >= self.max_len:
            return LENGTH      # cache slot full — nothing further fits
        return None

    def _evict(self, slot: int, reason: str) -> None:
        st = self._slots[slot]
        self._slots[slot] = None
        self.cache.release(slot)
        SERVING_EVICTIONS.add(1)
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)
        st.req._finish(reason)

    # -- gauges --------------------------------------------------------------
    def _note_ms(self, gauge, attr: str, ms: float) -> None:
        old = getattr(self, attr)
        new = old + ms
        setattr(self, attr, new)
        gauge.add(int(new) - int(old))

    def _note_tokens(self, n: int) -> None:
        now = time.monotonic()
        self._window.append((now, n))
        while self._window and now - self._window[0][0] > 2.0:
            self._window.popleft()
        total = sum(c for _, c in self._window)
        window_span = now - self._window[0][0]
        if window_span > 0:
            SERVING_TOKENS_PER_S.set(max(1, int(total / window_span)))
