"""Continuous-batching inference engine (Orca-style) over the KV cache.

Request lifecycle::

    submit() ──► bounded queue ──► [admit: prefill into a free slot]
                                        │
        stream()/result() ◄── tokens ◄──┤  one jitted decode step per tick,
                                        │  batched over ALL occupied slots
                  [evict: eos / max_tokens / deadline / cancel / capacity]

A single scheduler thread owns the device state (params, cache buffers,
jit calls); ``submit`` may be called from any thread and only touches the
queue. Each tick the scheduler (1) admits waiting requests into free
slots — prefill-and-insert, one sequence at a time, streaming the first
token — and (2) runs ONE compiled decode step over the whole slot batch,
so a late arrival starts generating next tick without draining anyone
(the reference's AnalysisPredictor has no such path; batching there is
caller-side). Finished sequences release their slot between ticks; the
batch never stalls on the longest request.

Jit surface: exactly two programs in steady state — a decode step at the
fixed (n_slots,) batch shape, and a prefill per prompt-length bucket
(prompts are end-padded to the next power of two, which causality makes
exact). Cache buffers are donated through both, so serving allocates
nothing per token. ``FLAGS_serving_jit=0`` swaps in an un-jitted
full-recompute reference decode (same scheduler, same sampling) as the
numerics escape hatch.

Paged mode (``FLAGS_paged_kv=1`` or ``InferenceEngine(paged=True)``,
ISSUE 7) replaces the fixed per-slot buffers with a
:class:`~paddle_tpu.serving.kv_cache.PagedKVCache` block pool and
changes the tick loop in two ways:

- **chunked prefill**: admission no longer runs the whole prompt in one
  stalling pass — each tick advances every admitted-but-unprefilled
  slot by at most ``prefill_chunk`` tokens (``serving.prefill_chunk``
  spans), THEN runs the batched decode step, so a long prompt delays
  open streams by one chunk's work per tick instead of its full length;
- **block-capacity admission**: the ``prompt >= max_len`` hard reject
  is gone — a prompt up to ``cfg.seq_len - 1`` tokens is admitted
  whenever enough free blocks exist, and otherwise waits at the head of
  the queue until evictions free blocks (queue-until-available
  backpressure). If generation outruns the pool, the youngest slot is
  preempted back to the queue (``serving_preemptions`` gauge) and later
  resumes by re-prefilling its prompt + generated prefix — output
  streams are unaffected.

Speculative decoding (ISSUE 10, ``InferenceEngine(draft=(draft_cfg,
draft_params), spec_k=k)``): a small draft model (its OWN fixed-slot KV
cache, prefilled alongside the target's) proposes k tokens per slot per
tick, and the target model scores all k+1 positions in ONE batched
verify pass (:func:`~paddle_tpu.models.gpt_verify_step` /
``gpt_verify_step_paged``). Acceptance follows the standard
rejection-sampling rule (serving.sampling.spec_accept), so
temperature/top-k/top-p sampling keeps the target distribution exactly
and greedy output is token-identical to ``draft=None`` — the whole
propose+verify+accept tick is one compiled program, so a tick emits up
to k+1 tokens per stream for one dispatch. Draft contract: same
vocabulary, gpt_init-layout params (``models.gpt_truncate`` builds a
layer-truncated draft from the target for free). Rejected positions
leave stale K/V past the accepted length, which the position masks hide
until the next step overwrites them; in paged mode the accepted length
drives the same block accounting as the plain path, with tables grown
(non-preemptively) to k+1 tokens of headroom — when a slot cannot get
spec headroom the tick falls back to the plain one-token program.

Multi-chip decode (ISSUE 10, ``FLAGS_serving_mesh=D`` or
``InferenceEngine(mesh=...)``): decode slots shard over the mesh "data"
axis and weights shard Megatron-style over "model"
(models.gpt_param_specs transfers directly — the decode step is a pure
function over the param pytree), so one jitted tick runs over the whole
mesh with GSPMD deriving the collectives. The fixed cache shards its
slot dim, the paged pool partitions its blocks into per-shard ranges
(per-shard free lists + garbage sinks; see PagedKVCache(shards=D)), and
admission places each request in the shard with the most free blocks.
``FLAGS_serving_mesh=0`` (default) with no explicit mesh keeps the
single-chip engine unchanged.

Prefix sharing (ISSUE 11, ``FLAGS_prefix_cache=1`` or
``InferenceEngine(prefix_cache=True)``, paged mode only): admission
walks a host-side radix tree of cached prompt prefixes
(serving.prefix_cache.RadixPrefixCache). A hit splices the matched
(refcounted) pool blocks straight into the new slot's block table and
only the uncached TAIL is prefilled — chunked, through
``gpt_prefill_prefix``, which continues from an arbitrary (not
necessarily block-aligned) cached length; a partially-used last block
is copy-on-write duplicated first (one compiled ``_cow_jit`` pool-row
copy), since tree blocks are read-only to everyone but their original
writer. Releasing a slot unrefs its blocks instead of freeing them, a
fully-prefilled prompt is inserted back into the tree, and when the
pool runs dry the scheduler reclaims LRU tree leaves BEFORE falling
back to youngest-first preemption. Greedy output is pinned
token-identical to the cache-cold engine. Not combinable with
``draft=`` (the draft's fixed cache has no K/V for a skipped prefix —
sharing would force a full draft prefill and erase the win).

Constrained decoding (ISSUE 11, ``submit(constraint=...)`` with a
serving.constrained.TokenConstraint): each constrained request carries
a byte-DFA cursor; its per-state token mask rides into the SAME jitted
sampling program as a (slots, vocab) bool input, composing with
temperature/top-k/top-p, and the cursor advances host-side per emitted
token. A completed match stops the stream (finish_reason ``"stop"``).
Ticks whose batch holds a constrained row drop from the speculative to
the plain one-token program (counted by ``constrained_fallback_ticks``)
— a draft proposing through an automaton would otherwise get
unconstrained tokens accepted.

Overload hardening (ISSUE 13): deadlines propagate end to end —
``submit(deadline_s=...)`` stamps an absolute monotonic deadline, and a
request that expires while QUEUED is shed at the next tick before any
prefill is spent on it (``serving_deadline_sheds``; the front end turns
an empty-handed deadline finish into 503 + Retry-After). An attached
:class:`~paddle_tpu.serving.overload.OverloadController` (``overload=``)
gets queue-wait and tick-latency observations and steps the brownout
ladder: rung 1 drops speculative decode, rung 2 shrinks prefill chunks;
lane-aware rungs (token caps, sheds) are applied by the front end.
``overload=None`` (default) is pinned bit-identical. A
:class:`~paddle_tpu.serving.router.EngineRouter` fronts N replicas:
``replica_id`` tags this engine's spans and fault specs, ``failover``
holds the router's adoption hook (stamped onto every request), and
``adopt_request`` replays another replica's stream here through the
preemption-resume contract, token-identical because replicas share the
seed and the request keeps its rid. The replica lifecycle (ISSUE 14,
serving/lifecycle.py) adds three supervisor-facing hooks:
``warm_prefix`` (prefill-only radix re-warm in a dedicated rid space),
``evacuate`` (fail every open stream with :class:`ReplicaEvacuated` so
the router migrates them — the drain-shrink terminal step), and
``fail_at_tick`` (deterministic crash for replica_flap chaos / manual
replica kills).

Observability: gauges serving_queue_depth / serving_slot_occupancy /
serving_prefill_ms / serving_decode_ms / serving_tokens_per_s (sliding
window over the last N ticks) / serving_evictions /
serving_preemptions, kv_blocks_free / kv_blocks_used /
kv_fragmentation from the block pool, spec_proposed / spec_accepted /
spec_acceptance_rate from the speculative path and serving_shards for
the mesh, plus ``serving.prefill`` / ``serving.prefill_chunk`` /
``serving.decode_step`` trace spans (decode spans carry
proposed/accepted and per-shard load args) that ``tools/trace_report.py``
turns into prefill-vs-decode, prefill-starvation, speculation and
shard-balance verdicts.

Observability v2 (ISSUE 15): latency HISTOGRAMS recorded at the source
(serving_first_token_ms / serving_per_token_ms / serving_queue_wait_ms
/ serving_decode_tick_ms / serving_prefill_chunk_ms — live under the
front end's Prometheus ``GET /metrics``); CAUSAL TRACING — a request
submitted with ``trace=TraceContext`` stamps every span it touches
(prefill, each chunk, each decode tick via per-request
``serving.decode_tick`` events, the ``serving.failover_hop`` of an
adoption, ``serving.request_done``) with its trace id + flow events,
so one request renders as one connected chrome-trace timeline across
replicas (``tools/trace_report.py --section request``); and the CRASH
FLIGHT RECORDER — ``flight_dir=`` arms a process-wide bounded ring of
recent spans/gauge deltas that ``_abort`` and the watchdog-restart
path dump as self-contained chrome-trace files at the moment of
failure (pod-aware naming, multi-host merge in trace_report).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import native
from ..models.gpt import (gpt_decode_step, gpt_decode_step_paged,
                          gpt_forward, gpt_param_specs, gpt_prefill,
                          gpt_prefill_chunk, gpt_prefill_prefix,
                          gpt_verify_step, gpt_verify_step_paged)
from ..monitor.stats import (CONSTRAINED_FALLBACK_TICKS,
                             CONSTRAINED_REQUESTS, FAULTS_INJECTED,
                             MOE_EXPERT_LOAD, MOE_EXPERT_SHARE_PCT,
                             MOE_TOKENS_DROPPED,
                             PREFIX_COW_COPIES, SERVING_DEADLINE_SHEDS,
                             SERVING_DECODE_MS, SERVING_DECODE_TICK_MS,
                             SERVING_EVICTIONS, SERVING_FIRST_TOKEN_MS,
                             SERVING_PER_TOKEN_MS, SERVING_PREEMPTIONS,
                             SERVING_PREFILL_CHUNK_MS, SERVING_PREFILL_MS,
                             SERVING_QUEUE_DEPTH, SERVING_QUEUE_WAIT_MS,
                             SERVING_SHARDS, SERVING_SLOT_OCCUPANCY,
                             SERVING_TOKENS_PER_S,
                             SERVING_WATCHDOG_RESTARTS,
                             SERVING_WATCHDOG_TRIPS,
                             SPEC_ACCEPTANCE_RATE, SPEC_ACCEPTED,
                             SPEC_PROPOSED)
from ..resilience import faults as _faults
from ..resilience.sentinel import logits_finite
from ..monitor.flight import arm_flight_recorder, dump_flight
from ..monitor.trace import (emit_complete, emit_flow, emit_instant,
                             recording, span)
from .kv_cache import KVCache, PagedKVCache, cache_insert
from .prefix_cache import RadixPrefixCache
from .sampling import (DRAFT_SALT, sample_tokens, sample_tokens_streams,
                       spec_accept, stream_keys)

__all__ = ["InferenceEngine", "GenerationRequest", "QueueFull",
           "WatchdogTripped", "ReplicaEvacuated"]

_CACHE_SPEC = P("data", None, "model", None, None)

# rid floor of the prefix re-warm request space (lifecycle.py): warm
# prefills draw RNG streams that can never collide with, or shift the
# numbering of, live request ids — rejoined replicas stay token-identical
_WARM_RID_BASE = 2**30


class QueueFull(RuntimeError):
    """submit() backpressure: the bounded request queue is at capacity."""


# finish reasons
EOS = "eos"
LENGTH = "length"
DEADLINE = "deadline"
CANCELLED = "cancelled"
SHUTDOWN = "shutdown"
ERROR = "error"
STOP = "stop"        # constrained decoding: the token-mask automaton
#                      reached a complete match — nothing more to emit
WATCHDOG = "watchdog"  # the per-tick NaN sentinel found this stream's
#                        logits poisoned; the engine restarted around it


class WatchdogTripped(RuntimeError):
    """Carried as the ``error`` of a request the serving watchdog failed:
    its decode logits went non-finite (poisoned KV/weights/activations).
    Healthy streams in the same batch are resumed, token-identical."""


class ReplicaEvacuated(RuntimeError):
    """Raised by the scheduler when :meth:`InferenceEngine.evacuate` asks
    it to stop: every open stream fails with this cause, which a router
    failover hook turns into survivor adoption (token-identical replay) —
    the drain-shrink terminal step of the replica lifecycle (ISSUE 14)."""


class GenerationRequest:
    """Per-request future returned by :meth:`InferenceEngine.submit`.

    Tokens stream in as the scheduler generates them: ``stream()`` yields
    them live, ``result()`` blocks for the full list, ``finish_reason``
    says why generation stopped (eos/length/deadline/cancelled/shutdown).
    Engines built with a tokenizer also offer ``stream_text()`` /
    ``text()`` — live detokenized text (specials skipped, split utf-8
    sequences held until complete).
    """

    def __init__(self, prompt, max_new_tokens: int, temperature: float,
                 top_k: int, top_p: float, eos_id: Optional[int],
                 deadline: Optional[float], constraint=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_id = eos_id
        self.deadline = deadline          # absolute time.monotonic() or None
        self.constraint = constraint      # ConstraintCursor (scheduler-owned)
        self.rid = 0                      # engine-assigned request id: the
        #                                   RNG stream identity (sampling.py)
        self.trace = None                 # TraceContext (ISSUE 15) or None:
        #                                   the request's causal identity,
        #                                   surviving failover/rejoin hops
        self.tokens: List[int] = []       # generated ids (includes eos)
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._cancelled = False
        self._t_first = None              # monotonic time of the first token
        self._tokenizer = None            # set by engines with a text front end
        # paged-mode preemption: (cached-prefix tokens, last token) to
        # re-prefill from when the request is re-admitted
        self._resume = None
        # EngineRouter failover hook: called (req, err) when the OWNING
        # replica dies; True = a survivor adopted this request and the
        # error must NOT finish it (see router.py)
        self._failover = None
        self._t_submit = 0.0              # monotonic enqueue time (queue-wait)
        self._cv = threading.Condition()

    # -- scheduler side ------------------------------------------------------
    def _push(self, tok: int) -> None:
        with self._cv:
            self.tokens.append(tok)
            if self._t_first is None:
                self._t_first = time.monotonic()
                if self._t_submit:
                    SERVING_FIRST_TOKEN_MS.observe(
                        (self._t_first - self._t_submit) * 1e3)
            self._cv.notify_all()

    def _finish(self, reason: str, error: Optional[BaseException] = None):
        if reason == ERROR and self._failover is not None:
            # replica-level death (never a per-request verdict like
            # watchdog/deadline): offer the stream to the router before
            # failing it — adoption replays it on a survivor
            handler, self._failover = self._failover, None
            try:
                if handler(self, error):
                    return          # adopted: a survivor owns this now
            except BaseException:  # noqa: BLE001 — failover must never mask
                pass               # the original error; fall through to it
        finished = False
        with self._cv:
            if self.finish_reason is None:
                self.finish_reason = reason
                self.error = error
                finished = True
            self._cv.notify_all()
        if not finished:
            return
        if self._t_first is not None and len(self.tokens) >= 2:
            # the steady-state inter-token rate the client saw, stalls
            # and failover hops included (bench's hand-collected twin)
            SERVING_PER_TOKEN_MS.observe(
                (time.monotonic() - self._t_first) * 1e3
                / (len(self.tokens) - 1))
        if self.trace is not None and recording():
            t = time.perf_counter()
            emit_complete("serving.request_done", t, 0.0, cat="serving",
                          args=self.trace.args(rid=self.rid, reason=reason,
                                               tokens=len(self.tokens)))
            emit_flow("f", self.trace.trace_id, t)

    # -- user side -----------------------------------------------------------
    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    def cancel(self) -> None:
        """Ask the scheduler to drop this request at its next tick (or at
        admission, if still queued)."""
        self._cancelled = True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until generation stops; returns the generated ids (the
        tokens produced before an eviction are kept — a deadline/cancel
        result is the partial sequence)."""
        with self._cv:
            if not self._cv.wait_for(lambda: self.finish_reason is not None,
                                     timeout):
                raise TimeoutError("generation still in progress")
        if self.error is not None:
            raise RuntimeError("generation failed") from self.error
        return list(self.tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as they are generated; returns when finished."""
        i = 0
        while True:
            with self._cv:
                if not self._cv.wait_for(
                        lambda: len(self.tokens) > i
                        or self.finish_reason is not None, timeout):
                    raise TimeoutError("generation still in progress")
                fresh = self.tokens[i:]
                finished = self.finish_reason is not None
            for t in fresh:
                yield t
            i += len(fresh)
            if finished and i >= len(self.tokens):
                if self.error is not None:
                    raise RuntimeError("generation failed") from self.error
                return

    def stream_text(self, timeout: Optional[float] = None):
        """Yield decoded text pieces as tokens arrive (engine must have a
        tokenizer). Special ids are skipped; a token that ends mid-utf-8
        is held until its sequence completes."""
        if self._tokenizer is None:
            raise RuntimeError("engine has no tokenizer — pass "
                               "InferenceEngine(tokenizer=...)")
        detok = self._tokenizer.stream_detokenizer()
        for tok in self.stream(timeout):
            piece = detok.push(tok)
            if piece:
                yield piece
        tail = detok.flush()
        if tail:
            yield tail

    def text(self, timeout: Optional[float] = None) -> str:
        """Block until generation stops; returns the decoded text."""
        if self._tokenizer is None:
            raise RuntimeError("engine has no tokenizer — pass "
                               "InferenceEngine(tokenizer=...)")
        return self._tokenizer.decode(self.result(timeout),
                                      skip_special=True)


class _HostCall:
    """One cross-thread closure parked for the scheduler
    (:meth:`InferenceEngine.run_on_scheduler`): the result/error slot
    plus a completion event the submitting thread blocks on."""

    __slots__ = ("fn", "result", "error", "done")

    def __init__(self, fn):
        self.fn = fn
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def run(self, eng) -> None:
        try:
            self.result = self.fn(eng)
        except BaseException as e:  # noqa: BLE001 — a host-call error is
            self.error = e          # the caller's, never a scheduler crash
        self.done.set()

    def fail(self, err: BaseException) -> None:
        self.error = err
        self.done.set()


class _Slot:
    """Host-side state of one occupied cache slot."""

    __slots__ = ("req", "length", "last_token", "generated", "pending",
                 "resume_last", "admit_order", "tail_mode")

    def __init__(self, req: GenerationRequest, length: int, last_token: int):
        self.req = req
        self.length = length          # tokens whose K/V are in the cache
        self.last_token = last_token  # input of the next decode step
        self.generated = 1            # prefill already streamed one token
        self.pending = None           # paged: prompt tokens not yet prefilled
        self.resume_last = None       # paged: last token of a preempted run
        self.admit_order = 0          # paged: preemption picks the youngest
        self.tail_mode = False        # prefix hit: chunks continue from an
        #                               unaligned cached length (_tail_jit)


class InferenceEngine:
    """Continuous-batching generation server for a functional GPT model.

    ::

        eng = InferenceEngine(cfg, params, n_slots=8)
        req = eng.submit(prompt_ids, max_new_tokens=64, temperature=0.8)
        for tok in req.stream(): ...
        eng.shutdown()

    ``params`` is a gpt_init-layout pytree (flat blocks — stage-stacked
    training layouts must be unstacked first).

    ``int8_weights=True`` quantizes the block matmul weights to int8
    per-channel (models.gpt.quantize_gpt_weights) for the DECODE step —
    the steady-state batched tick runs through the Pallas fused int8
    matmul (ops/int8_matmul.py; dequant in the kernel epilogue, int8 at
    2x the bf16 MXU rate on v5e). Prefill and the FLAGS_serving_jit=0
    reference decode keep the fp weights, so admission numerics are
    unchanged; decode tokens are near-greedy-identical but not pinned
    bit-for-bit (weight rounding). Default off.

    ``paged`` (None = follow FLAGS_paged_kv) swaps the fixed-slot cache
    for a PagedKVCache block pool: per-slot memory proportional to live
    tokens, admission gated on free BLOCKS instead of ``max_len``
    (``max_len`` is ignored; the per-slot ceiling is ``cfg.seq_len``),
    prompt prefill chunked at ``prefill_chunk`` tokens per tick and
    interleaved with decode, and the Pallas paged-attention kernel on
    TPU. ``block_size`` tokens per pool block; ``n_blocks`` defaults to
    worst-case (every slot at seq_len) — size it smaller to actually
    overcommit. Greedy output is token-identical to paged=False.

    ``draft=(draft_cfg, draft_params)`` enables speculative decoding:
    ``spec_k`` proposals per slot per tick from the draft, one target
    verify pass, rejection-sampling acceptance — greedy token-identical
    to ``draft=None``, sampled output keeps the target distribution.
    The draft must share the vocabulary and its positional table must
    cover the engine's ``max_len``. Requires FLAGS_serving_jit=1 (the
    reference escape hatch decodes one token at a time and must not be
    flipped mid-run on an engine holding a draft cache).

    ``mesh`` (None = follow FLAGS_serving_mesh) runs the decode over a
    multi-chip mesh: slots shard over "data", weights over "model";
    ``n_slots`` must divide by the data degree and ``n_heads`` (target
    and draft) by the model degree. Not combinable with
    ``int8_weights`` (the quantized pytree has no spec table yet).

    ``tokenizer`` (serving.tokenizer.ByteTokenizer or anything with the
    same encode/decode/stream_detokenizer surface) enables the text
    front end: ``submit(text=...)`` and request ``stream_text()``.

    ``prefix_cache`` (None = follow FLAGS_prefix_cache; needs paged
    mode, not combinable with ``draft``) turns on radix-tree prefix
    sharing: prompts that repeat a cached prefix splice its refcounted
    blocks instead of re-prefilling, with copy-on-write on a
    partially-used last block and LRU-by-leaf reclaim ahead of
    preemption. Greedy output stays token-identical to the cold cache.

    ``watchdog`` (True or a dict; default off, and when off every
    compiled program is bit-identical to a watchdog-free build) arms the
    per-tick NaN/latency sentinel: each decode tick also returns a
    per-slot all-finite verdict over the logits; a poisoned slot FAILS
    only its own request (finish_reason ``"watchdog"``, error
    :class:`WatchdogTripped`) and the engine auto-restarts from the last
    healthy state — healthy streams are requeued with their token
    history and replayed through the preemption-resume path
    (token-identical continuations), the device cache and prefix tree
    are rebuilt from scratch. Composes with ``draft=`` (ISSUE 14): the
    speculative verify program computes the same per-slot verdict over
    its k+1 verify positions, and a restart rebuilds the draft's KV
    cache alongside the target's (the prefill paths re-seed both).
    Options: ``latency_budget_ms`` (None disables the latency rung)
    with ``latency_trips`` consecutive slow ticks per stall verdict,
    and ``max_restarts`` before the engine fails open requests loudly.

    ``flight_dir`` (ISSUE 15) arms the process-wide crash flight
    recorder (``monitor.arm_flight_recorder`` — idempotent, shared by
    every engine in the process) and makes the scheduler-abort and
    watchdog-restart paths dump the ring of recent spans/gauge deltas
    there as a self-contained chrome-trace at the moment of failure.

    ``embedding_tables`` (ISSUE 16) arms the recommender ranking path:
    a ``{name: (rows, dim) array}`` dict (optionally ``(tables,
    score_fn)`` to score with a trained model, or a ready
    :class:`~paddle_tpu.sparse.EmbeddingRanker`) is placed row-sharded
    over the engine mesh's "model" axis and :meth:`rank` resolves a
    request's sparse features against it inside one jitted lookup+score
    step (the shard_map all-to-all exchange — no host hop between
    lookup and MLP). The HTTP frontend exposes it as ``POST /v1/rank``.
    Independent of the generation path: no compiled generation program
    changes when it is armed.
    """

    def __init__(self, cfg, params, n_slots: int = 4,
                 max_len: Optional[int] = None, queue_size: int = 64,
                 eos_id: Optional[int] = None, seed: int = 0,
                 int8_weights: bool = False, paged: Optional[bool] = None,
                 block_size: int = 16, n_blocks: Optional[int] = None,
                 prefill_chunk: int = 64, tps_window_ticks: int = 64,
                 draft=None, spec_k: int = 4, mesh=None, tokenizer=None,
                 prefix_cache: Optional[bool] = None, watchdog=None,
                 overload=None, replica_id: Optional[int] = None,
                 flight_dir: Optional[str] = None,
                 embedding_tables=None):
        # per-tick NaN/latency sentinel + auto-restart (off by default;
        # when off the engine's compiled programs are bit-identical to a
        # build without it — the health output is gated at trace time)
        if watchdog:
            defaults = {"latency_budget_ms": None, "latency_trips": 3,
                        "max_restarts": 3}
            if watchdog is not True:
                unknown = set(dict(watchdog)) - set(defaults)
                if unknown:
                    raise ValueError(f"unknown watchdog option(s) "
                                     f"{sorted(unknown)}")
                defaults.update(dict(watchdog))
            self._watchdog = defaults
        else:
            self._watchdog = None
        self._restarts = 0
        self._slow_ticks = 0
        if getattr(cfg, "fused_mlp", None) is None:
            # pin the fused-MLP choice NOW (graftlint GL002): prefill
            # programs compile lazily per prompt-length bucket, so a
            # FLAGS_fused_kernels flip mid-serving would otherwise split
            # the engine across fused and unfused programs per bucket
            import dataclasses as _dc

            cfg = _dc.replace(cfg, fused_mlp=bool(native.fused_kernels[0]))
        self.cfg = cfg
        self._mesh = self._resolve_mesh(mesh)
        self._shards = int(self._mesh.shape["data"]) \
            if self._mesh is not None else 1
        if self._mesh is not None:
            if int8_weights:
                raise ValueError("int8_weights and mesh are not yet "
                                 "combinable (no spec table for the "
                                 "quantized pytree)")
            if n_slots % self._shards != 0:
                raise ValueError(f"n_slots={n_slots} not divisible by the "
                                 f"data degree {self._shards}")
            model_deg = int(self._mesh.shape["model"])
            if cfg.n_heads % model_deg != 0:
                raise ValueError(f"n_heads={cfg.n_heads} not divisible by "
                                 f"the model degree {model_deg}")
        self._moe = bool(getattr(cfg, "moe_layer_ids", ()))
        if self._moe:
            import dataclasses as _dc

            if int8_weights:
                raise ValueError("int8_weights and MoE are not combinable "
                                 "(no quantized layout for the expert "
                                 "pytree)")
            if draft is not None:
                raise ValueError("draft= and MoE are not combinable: "
                                 "speculative verify has no routed-expert "
                                 "path (gpt_verify_step rejects MoE)")
            if self._mesh is not None:
                model_deg = int(self._mesh.shape["model"])
                if model_deg > 1 and cfg.moe_experts % model_deg != 0:
                    raise ValueError(
                        f"moe_experts={cfg.moe_experts} not divisible by "
                        f"the model degree {model_deg} — experts shard "
                        "over the \"model\" axis")
                cfg = _dc.replace(
                    cfg, moe_axis="model" if model_deg > 1 else None)
            else:
                cfg = _dc.replace(cfg, moe_axis=None)
            self.cfg = cfg
        self._params = self._put_params(cfg, params)
        self.int8_weights = bool(int8_weights)
        if int8_weights:
            from ..models.gpt import quantize_gpt_weights
            from ..monitor.stats import INT8_MATMUL_CALLS

            self._decode_params = jax.device_put(
                quantize_gpt_weights(params))
            INT8_MATMUL_CALLS.add()
        else:
            self._decode_params = self._params
        self.paged = native.paged_kv[0] if paged is None else bool(paged)
        # cache construction args, kept for the watchdog's restart path
        # (a restart rebuilds the device cache from scratch)
        self._cache_args = (max_len, n_blocks, block_size)
        if self.paged:
            self.cache = PagedKVCache(cfg, n_slots, n_blocks=n_blocks,
                                      block_size=block_size,
                                      shards=self._shards)
            self.block_size = self.cache.block_size
            self.max_len = cfg.seq_len   # positional table = per-slot cap
            if prefill_chunk % self.block_size != 0:
                raise ValueError(
                    f"prefill_chunk={prefill_chunk} must be a multiple of "
                    f"block_size={self.block_size} (chunks must start "
                    "block-aligned)")
            self.prefill_chunk = int(prefill_chunk)
            self._decode_paged_jit = jax.jit(self._decode_paged_fn,
                                             donate_argnums=(1, 2))
            self._chunk_jit = jax.jit(self._chunk_fn, donate_argnums=(1, 2))
            if self._mesh is not None:
                self.cache.kb = self._put_cache(self.cache.kb)
                self.cache.vb = self._put_cache(self.cache.vb)
        else:
            self.cache = KVCache(cfg, n_slots, max_len)
            self.max_len = self.cache.max_len
            self.prefill_chunk = None
            if self._mesh is not None:
                self.cache.k = self._put_cache(self.cache.k)
                self.cache.v = self._put_cache(self.cache.v)
        self.n_slots = self.cache.n_slots
        use_prefix = native.prefix_cache[0] if prefix_cache is None \
            else bool(prefix_cache)
        if use_prefix and not self.paged:
            raise ValueError("prefix_cache requires the paged KV cache "
                             "(FLAGS_paged_kv=1 or paged=True) — sharing "
                             "needs block-table indirection")
        if use_prefix and draft is not None:
            raise ValueError("prefix_cache and draft= are not combinable: "
                             "the draft's fixed cache holds no K/V for a "
                             "skipped prefix, so every hit would force a "
                             "full draft prefill")
        if use_prefix and self._moe:
            raise ValueError("prefix_cache and MoE are not combinable: "
                             "prefix reuse verifies through "
                             "gpt_verify_step_paged, which has no "
                             "routed-expert path")
        if use_prefix:
            self._prefix = RadixPrefixCache(self.cache)
            self._tail_jit = jax.jit(self._tail_fn, donate_argnums=(1, 2))
            self._cow_jit = jax.jit(self._cow_fn, donate_argnums=(0, 1))
        else:
            self._prefix = None
        self._init_draft(draft, spec_k)
        # the draft always decodes against its own fixed-slot cache —
        # k short steps over a small model don't need paging (built here
        # AND by the watchdog restart's _reset_cache on its thread)
        self.draft_cache = self._build_draft_cache() \
            if self.draft is not None else None
        self.tokenizer = tokenizer
        # all-true token mask reused by every unconstrained tick: host
        # template for constrained batches, device-resident copy so the
        # common path ships no (slots, vocab) buffer per tick
        self._ones_mask = np.ones((self.n_slots, cfg.vocab_size), bool)
        self._mask_dev = jax.device_put(self._ones_mask)
        self.eos_id = eos_id
        self._queue: collections.deque = collections.deque()
        self._queue_size = int(queue_size)
        self._cv = threading.Condition()
        self._slots: List[Optional[_Slot]] = [None] * self.n_slots
        self._stop = False
        self._drain = True
        self._error: Optional[BaseException] = None  # scheduler crash cause
        self._base_key = jax.random.key(seed)
        self._rid = 0            # next request id (per-request RNG stream)
        self._warm_seq = 0       # warm_prefix sequence (its own rid space)
        self._evacuate = False   # lifecycle drain: scheduler raises
        #                          ReplicaEvacuated at its next loop check
        self._die_tick = None    # lifecycle chaos: fail_at_tick target
        self._ticks = 0          # scheduler loop iterations (span tagging)
        self._admit_seq = 0
        self._spec_prop = 0      # lifetime draft proposals / acceptances
        self._spec_acc = 0       # behind the acceptance-rate gauge
        # float running totals behind the int ms gauges (prefetch.py idiom:
        # sub-ms ticks still accumulate)
        self._prefill_ms = 0.0
        self._decode_ms = 0.0
        # tokens/s: sliding window over the last N tick completions, so a
        # load spike/dip shows in trace reports instead of being averaged
        # into the engine's lifetime
        self._window: collections.deque = collections.deque(
            maxlen=max(2, int(tps_window_ticks)))  # (t, n_tokens)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(1, 2))
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(1, 2))
        SERVING_SHARDS.set(self._shards)
        # overload-hardening surface (ISSUE 13): the brownout controller
        # (None = every schedule decision bit-identical to a build
        # without it), the router-assigned replica identity, the
        # router-installed failover hook stamped onto each request, and
        # the scheduler heartbeat behind the router's tick-age health
        self.overload = overload
        self.replica_id = replica_id
        self.failover = None
        # crash flight recorder (ISSUE 15): arming is process-global and
        # idempotent — every engine in the process shares one ring, and
        # the abort/watchdog paths dump it the moment they fire
        self.flight_dir = flight_dir
        if flight_dir:
            arm_flight_recorder(flight_dir)
        # serving-side sparse lookup (ISSUE 16): tables placed over THIS
        # engine's mesh; built before the scheduler thread starts so a
        # rank() race with startup is impossible
        self._ranker = None
        if embedding_tables is not None:
            from ..sparse.ranking import EmbeddingRanker

            if isinstance(embedding_tables, EmbeddingRanker):
                self._ranker = embedding_tables
            elif isinstance(embedding_tables, tuple):
                tables, score_fn = embedding_tables
                self._ranker = EmbeddingRanker(tables, score_fn=score_fn,
                                               mesh=self._mesh)
            else:
                self._ranker = EmbeddingRanker(dict(embedding_tables),
                                               mesh=self._mesh)
        self._last_tick_t = time.monotonic()
        # cross-host fleet (ISSUE 19): closures parked by other threads
        # for the scheduler to run between ticks — the KV export/import
        # path touches the donated pool buffers, which only the
        # scheduler thread may do (guarded by self._cv)
        self._host_calls: collections.deque = collections.deque()
        self._thread = threading.Thread(target=self._run,
                                        name="serving-scheduler", daemon=True)
        self._thread.start()

    # -- multi-chip placement ------------------------------------------------
    def _resolve_mesh(self, mesh):
        """Explicit ``mesh`` wins; else FLAGS_serving_mesh=D builds a
        (data=D, model=rest) mesh over every visible device; else None
        (single chip — the pinned PR-7 path)."""
        if mesh is not None:
            return mesh
        degree = int(native.serving_mesh[0])
        if degree <= 0:
            return None
        from jax.sharding import Mesh

        from ..parallel.mesh import AXES
        devices = jax.devices()
        if len(devices) % degree != 0:
            raise ValueError(
                f"FLAGS_serving_mesh={degree} does not divide the "
                f"{len(devices)} visible devices")
        arr = np.array(devices).reshape(degree, 1, 1,
                                        len(devices) // degree)
        return Mesh(arr, AXES)

    def _put_params(self, cfg, params):
        if self._mesh is None:
            return jax.device_put(params)
        from ..parallel.sharding import shard_params
        return shard_params(params, gpt_param_specs(cfg), self._mesh)

    def _put_cache(self, buf):
        return jax.device_put(buf, NamedSharding(self._mesh, _CACHE_SPEC))

    # -- speculative-decoding setup ------------------------------------------
    def _init_draft(self, draft, spec_k: int) -> None:
        if draft is None:
            self.draft = None
            self.draft_cfg = None
            self.spec_k = 0
            return
        draft_cfg, draft_params = draft
        if int(spec_k) < 1:
            raise ValueError(f"spec_k={spec_k} must be >= 1")
        if draft_cfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{self.cfg.vocab_size} (the acceptance rule compares "
                "distributions over one vocabulary)")
        # paged chunks are block-padded, so the draft cache (and its
        # positional table) must cover max_len rounded up to a block
        draft_len = self.max_len if not self.paged else \
            -(-self.max_len // self.block_size) * self.block_size
        if draft_cfg.seq_len < draft_len:
            raise ValueError(
                f"draft seq_len {draft_cfg.seq_len} < engine cache span "
                f"{draft_len} — the draft must reach every position the "
                "target can")
        if getattr(draft_cfg, "fused_mlp", None) is None:
            import dataclasses as _dc

            draft_cfg = _dc.replace(
                draft_cfg, fused_mlp=bool(native.fused_kernels[0]))
        if self._mesh is not None:
            model_deg = int(self._mesh.shape["model"])
            if draft_cfg.n_heads % model_deg != 0:
                raise ValueError(
                    f"draft n_heads={draft_cfg.n_heads} not divisible by "
                    f"the model degree {model_deg}")
        self.draft_cfg = draft_cfg
        self._draft_params = self._put_params(draft_cfg, draft_params)
        self.draft = (draft_cfg, self._draft_params)
        self.spec_k = int(spec_k)
        self._draft_len = draft_len
        self._prefill_spec_jit = jax.jit(self._prefill_spec_fn,
                                         donate_argnums=(2, 3, 4, 5))
        if self.paged:
            self._spec_paged_jit = jax.jit(self._spec_paged_fn,
                                           donate_argnums=(2, 3, 4, 5))
            self._chunk_spec_jit = jax.jit(self._chunk_spec_fn,
                                           donate_argnums=(2, 3, 4, 5))
        else:
            self._spec_jit = jax.jit(self._spec_fn,
                                     donate_argnums=(2, 3, 4, 5))

    def _build_draft_cache(self):
        """Fresh zeroed draft KV cache (construction and the watchdog
        restart both route here, so the rebuild matches the original)."""
        cache = KVCache(self.draft_cfg, self.n_slots,
                        max_len=self._draft_len)
        if self._mesh is not None:
            cache.k = self._put_cache(cache.k)
            cache.v = self._put_cache(cache.v)
        return cache

    # -- compiled programs ---------------------------------------------------
    def _sample_args(self, logits, base_key, rids, steps, temps, top_ks,
                    top_ps, mask):
        keys = stream_keys(base_key, rids, steps)
        return sample_tokens_streams(logits, keys, temps, top_ks, top_ps,
                                     mask=mask)

    def _decode_fn(self, params, k, v, positions, tokens, base_key, rids,
                   steps, temps, top_ks, top_ps, mask):
        got = gpt_decode_step(self.cfg, params, (k, v), positions, tokens)
        logits, (k, v) = got[0], got[1]
        toks = self._sample_args(logits, base_key, rids, steps, temps,
                                 top_ks, top_ps, mask)
        out = (toks,)
        if self._watchdog is not None:
            # per-slot finite verdict — gated at TRACE time, so a
            # watchdog-off engine compiles the exact historical program
            out = out + (logits_finite(logits),)
        out = out + (k, v)
        if self._moe:
            # (counts (E,), dropped) router stats — always LAST so the
            # tick's unpack can peel them off uniformly
            out = out + (got[2],)
        return out

    def _prefill_fn(self, params, k, v, tokens, slot, true_len, key, temp,
                    top_k, top_p, mask):
        # tokens (1, S_pad) end-padded; causality keeps positions < true_len
        # exact, and the logits/cache rows past true_len are never read
        logits, (ke, ve) = gpt_prefill(self.cfg, params, tokens)
        k, v = cache_insert(k, v, slot, ke[0], ve[0])
        last = jax.lax.dynamic_index_in_dim(logits[0], true_len - 1, 0,
                                            keepdims=False)
        tok = sample_tokens(last[None], key, temp[None], top_k[None],
                            top_p[None], mask=mask)[0]
        return tok, k, v

    def _prefill_spec_fn(self, params, dparams, k, v, dk, dv, tokens, slot,
                         true_len, key, temp, top_k, top_p, mask):
        # target prefill + draft prefill in ONE program: both caches seed
        # the same slot so the first speculative tick can draft at once
        logits, (ke, ve) = gpt_prefill(self.cfg, params, tokens)
        k, v = cache_insert(k, v, slot, ke[0], ve[0])
        _, (dke, dve) = gpt_prefill(self.draft_cfg, dparams, tokens)
        dk, dv = cache_insert(dk, dv, slot, dke[0], dve[0])
        last = jax.lax.dynamic_index_in_dim(logits[0], true_len - 1, 0,
                                            keepdims=False)
        tok = sample_tokens(last[None], key, temp[None], top_k[None],
                            top_p[None], mask=mask)[0]
        return tok, k, v, dk, dv

    def _decode_paged_fn(self, params, kb, vb, tables, positions, tokens,
                         base_key, rids, steps, temps, top_ks, top_ps,
                         mask):
        got = gpt_decode_step_paged(
            self.cfg, params, (kb, vb), tables, positions, tokens)
        logits, (kb, vb) = got[0], got[1]
        toks = self._sample_args(logits, base_key, rids, steps, temps,
                                 top_ks, top_ps, mask)
        out = (toks,)
        if self._watchdog is not None:
            out = out + (logits_finite(logits),)
        out = out + (kb, vb)
        if self._moe:
            out = out + (got[2],)
        return out

    def _tail_fn(self, params, kb, vb, table_row, tokens, start):
        # prefix-cache tail chunk: continue a prefill from an UNALIGNED
        # cached length (the radix match ends wherever the shared prompt
        # diverges); only the final chunk's last live row is read
        logits, (kb, vb) = gpt_prefill_prefix(
            self.cfg, params, (kb, vb), table_row, tokens, start)
        return logits, kb, vb

    def _cow_fn(self, kb, vb, src, dst):
        # copy-on-write: duplicate ONE pool block's rows (every layer)
        # into a freshly-allocated block before the slot extends it
        kr = jax.lax.dynamic_slice_in_dim(kb, src, 1, axis=0)
        vr = jax.lax.dynamic_slice_in_dim(vb, src, 1, axis=0)
        kb = jax.lax.dynamic_update_slice_in_dim(kb, kr, dst, axis=0)
        vb = jax.lax.dynamic_update_slice_in_dim(vb, vr, dst, axis=0)
        return kb, vb

    def _chunk_fn(self, params, kb, vb, table_row, tokens, start):
        # one prefill chunk: writes the chunk's K/V into the pool, returns
        # the chunk logits (only the final chunk's last live row is read)
        logits, (kb, vb) = gpt_prefill_chunk(
            self.cfg, params, (kb, vb), table_row, tokens, start)
        return logits, kb, vb

    def _chunk_spec_fn(self, params, dparams, kb, vb, dk, dv, table_row,
                       slot, tokens, start):
        # paged target chunk + the same chunk appended to the draft's
        # fixed cache row (gpt_verify_step doubles as a chunk append)
        logits, (kb, vb) = gpt_prefill_chunk(
            self.cfg, params, (kb, vb), table_row, tokens, start)
        row_k = jax.lax.dynamic_slice_in_dim(dk, slot, 1, axis=0)
        row_v = jax.lax.dynamic_slice_in_dim(dv, slot, 1, axis=0)
        _, (row_k, row_v) = gpt_verify_step(
            self.draft_cfg, dparams, (row_k, row_v),
            jnp.reshape(start, (1,)), tokens)
        dk = jax.lax.dynamic_update_slice_in_dim(dk, row_k, slot, axis=0)
        dv = jax.lax.dynamic_update_slice_in_dim(dv, row_v, slot, axis=0)
        return logits, kb, vb, dk, dv

    def _draft_propose(self, dparams, dk, dv, positions, tokens, base_key,
                       rids, steps, temps, top_ks, top_ps):
        """spec_k autoregressive draft steps (unrolled into the one spec
        program): returns proposed tokens (B, K), the distributions they
        were drawn from (B, K, V), and the updated draft cache."""
        cur = tokens
        d_toks, d_logits = [], []
        for j in range(self.spec_k):
            lg, (dk, dv) = gpt_decode_step(self.draft_cfg, dparams,
                                           (dk, dv), positions + j, cur)
            keys = stream_keys(base_key, rids, steps + j)
            dkeys = jax.vmap(
                lambda kk: jax.random.fold_in(kk, DRAFT_SALT))(keys)
            cur = sample_tokens_streams(lg, dkeys, temps, top_ks, top_ps)
            d_toks.append(cur)
            d_logits.append(lg)
        return (jnp.stack(d_toks, axis=1), jnp.stack(d_logits, axis=1),
                dk, dv)

    def _spec_fn(self, params, dparams, k, v, dk, dv, positions, tokens,
                 base_key, rids, steps, temps, top_ks, top_ps):
        d_toks, d_logits, dk, dv = self._draft_propose(
            dparams, dk, dv, positions, tokens, base_key, rids, steps,
            temps, top_ks, top_ps)
        vtokens = jnp.concatenate([tokens[:, None], d_toks], axis=1)
        t_logits, (k, v) = gpt_verify_step(self.cfg, params, (k, v),
                                           positions, vtokens)
        keys = stream_keys(base_key, rids, steps)
        out, n_emit = spec_accept(t_logits, d_logits, d_toks, keys, temps,
                                  top_ks, top_ps)
        if self._watchdog is not None:
            # per-slot finite verdict over ALL k+1 verify positions —
            # trace-time gated like the plain tick, so watchdog=off spec
            # programs compile bit-identical to a watchdog-free build
            health = logits_finite(
                jnp.reshape(t_logits, (t_logits.shape[0], -1)))
            return out, n_emit, health, k, v, dk, dv
        return out, n_emit, k, v, dk, dv

    def _spec_paged_fn(self, params, dparams, kb, vb, dk, dv, tables,
                       positions, tokens, base_key, rids, steps, temps,
                       top_ks, top_ps):
        d_toks, d_logits, dk, dv = self._draft_propose(
            dparams, dk, dv, positions, tokens, base_key, rids, steps,
            temps, top_ks, top_ps)
        vtokens = jnp.concatenate([tokens[:, None], d_toks], axis=1)
        t_logits, (kb, vb) = gpt_verify_step_paged(
            self.cfg, params, (kb, vb), tables, positions, vtokens)
        keys = stream_keys(base_key, rids, steps)
        out, n_emit = spec_accept(t_logits, d_logits, d_toks, keys, temps,
                                  top_ks, top_ps)
        if self._watchdog is not None:
            health = logits_finite(
                jnp.reshape(t_logits, (t_logits.shape[0], -1)))
            return out, n_emit, health, kb, vb, dk, dv
        return out, n_emit, kb, vb, dk, dv

    # -- public API ----------------------------------------------------------
    def submit(self, prompt: Optional[Sequence[int]] = None,
               max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_id: Optional[int] = None, deadline_s: Optional[float] = None,
               block: bool = True, timeout: Optional[float] = None,
               text: Optional[str] = None,
               constraint=None, trace=None) -> GenerationRequest:
        """Queue a generation request; returns its streaming handle.

        Exactly one of ``prompt`` (token ids) and ``text`` must be given;
        ``text`` requires the engine's tokenizer, encodes through it, and
        defaults ``eos_id`` to the tokenizer's (so ``stream_text()``
        terminates naturally). Backpressure: when the bounded queue is
        full, ``block=True`` waits (up to ``timeout`` seconds) for space
        and raises :class:`QueueFull` on timeout; ``block=False`` raises
        immediately. ``deadline_s`` is a wall-clock budget from now — a
        request over budget is evicted with ``finish_reason="deadline"``
        wherever it is (queued or mid-decode).

        ``constraint`` (serving.constrained.TokenConstraint) masks every
        sampled token through the compiled automaton — structured
        decoding; the stream finishes with ``finish_reason="stop"`` when
        the match completes.

        ``trace`` (monitor.TraceContext, ISSUE 15) is the request's
        causal tracing identity — minted at HTTP admission by the front
        end and stamped onto every span/flow event the request touches,
        across failover hops. It never influences sampling: with tracing
        off the token stream is pinned bit-identical.
        """
        if text is not None:
            if prompt is not None:
                raise ValueError("pass prompt OR text, not both")
            if self.tokenizer is None:
                raise ValueError("submit(text=...) needs an engine "
                                 "tokenizer — InferenceEngine(tokenizer=...)")
            prompt = self.tokenizer.encode(text)
            if eos_id is None and self.eos_id is None:
                eos_id = self.tokenizer.eos_id
        if prompt is None:
            raise ValueError("provide a prompt (token ids) or text")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size >= self.max_len:
            # paged mode lifts this to the positional table (cfg.seq_len):
            # block capacity is checked at admission, not here
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to generate "
                + (f"(positional table seq_len={self.max_len})" if self.paged
                   else f"(cache max_len={self.max_len})"))
        if self.paged and \
                self.cache.blocks_for(prompt.size + 1) > \
                self.cache.max_slot_blocks:
            raise ValueError(
                f"prompt length {prompt.size} can never fit one shard of "
                f"the block pool ({self.cache.max_slot_blocks} blocks x "
                f"{self.block_size} tokens)")
        cursor = None
        if constraint is not None:
            if getattr(constraint, "vocab_size", self.cfg.vocab_size) \
                    > self.cfg.vocab_size:
                raise ValueError(
                    f"constraint vocab {constraint.vocab_size} exceeds the "
                    f"model vocab {self.cfg.vocab_size}")
            cursor = constraint.cursor() if hasattr(constraint, "cursor") \
                else constraint
            CONSTRAINED_REQUESTS.add(1)
        req = GenerationRequest(
            prompt, max_new_tokens, temperature, top_k, top_p,
            self.eos_id if eos_id is None else eos_id,
            None if deadline_s is None else time.monotonic() + deadline_s,
            constraint=cursor)
        req.trace = trace
        req._tokenizer = self.tokenizer
        with self._cv:
            self._check_open()
            if len(self._queue) >= self._queue_size:
                if not block:
                    raise QueueFull(
                        f"serving queue at capacity ({self._queue_size})")
                ok = self._cv.wait_for(
                    lambda: self._stop
                    or len(self._queue) < self._queue_size, timeout)
                if not ok:
                    raise QueueFull(
                        f"serving queue still full after {timeout}s")
                self._check_open()
            # the request id is the RNG stream identity: assigned in
            # submission order, so a stream's sampled tokens are a pure
            # function of (seed, rid) — batch neighbors can't perturb it
            req.rid = self._rid
            self._rid += 1
            req._failover = self.failover
            req._t_submit = time.monotonic()
            self._queue.append(req)
            SERVING_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()
        return req

    def adopt_request(self, req: GenerationRequest) -> None:
        """Router failover entry: enqueue a request ANOTHER replica was
        serving when it died. The preemption-resume contract rebuilds
        decode state from ``prompt + generated[:-1]`` with the last
        token restored, and the request KEEPS its rid — with replicas
        sharing a seed, the continuation is token-identical to the run
        the dead replica would have produced. Bypasses the queue bound
        (failover must not drop work a user already holds a handle to)."""
        if req.trace is not None:
            # the causal timeline continues on THIS replica: record the
            # hop so chrome-trace/request_report show one connected
            # request across the failover instead of two half-streams
            prev = getattr(req, "_replica", None)
            req.trace.hop(prev, self.replica_id)
            if recording():
                t = time.perf_counter()
                emit_complete(
                    "serving.failover_hop", t, 0.0, cat="serving",
                    args=req.trace.args(
                        rid=req.rid, hop_from=prev,
                        hop_to=self.replica_id))
                emit_flow("t", req.trace.trace_id, t)
        with self._cv:
            self._check_open()
            if req.tokens:
                seq = np.concatenate(
                    [req.prompt, np.asarray(req.tokens[:-1],
                                            np.int32)]).astype(np.int32)
                req._resume = (seq, int(req.tokens[-1]))
            else:
                req._resume = None      # nothing emitted: just start over
            req._failover = self.failover
            req._t_submit = time.monotonic()
            # keep future rids clear of the adopted one: rid collisions
            # would alias two requests onto one RNG stream
            self._rid = max(self._rid, req.rid + 1)
            self._queue.append(req)
            SERVING_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()

    # -- replica lifecycle (serving/lifecycle.py, ISSUE 14) ------------------
    def warm_prefix(self, prompt) -> GenerationRequest:
        """Queue a prefill-only background request — the radix re-warm
        primitive. The prompt is prefilled (and, in paged+prefix mode,
        inserted into the radix tree) and exactly one token is generated
        and discarded by the caller. The request id comes from a
        DEDICATED space above ``2**30``, so warm replay neither collides
        with nor shifts the numbering of live request ids — a rejoined
        replica's sampled streams stay pure functions of (seed, rid)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1 or prompt.size >= self.max_len:
            raise ValueError(f"warm prefix length {prompt.size} outside "
                             f"(0, {self.max_len})")
        req = GenerationRequest(prompt, 1, 0.0, 0, 1.0, None, None)
        req._tokenizer = self.tokenizer
        with self._cv:
            self._check_open()
            req.rid = _WARM_RID_BASE + self._warm_seq
            self._warm_seq += 1
            req._t_submit = time.monotonic()
            self._queue.append(req)        # warm runs pre-traffic: the
            SERVING_QUEUE_DEPTH.set(len(self._queue))  # bound is moot
            self._cv.notify_all()
        return req

    def evacuate(self) -> None:
        """Ask the scheduler to stop by FAILING every open stream with
        :class:`ReplicaEvacuated` — through the router failover hook,
        each one is adopted by a survivor and replayed token-identically
        (the preemption-resume contract). The drain-shrink terminal
        step: callers must have already stopped routing new work here."""
        with self._cv:
            self._evacuate = True
            self._cv.notify_all()

    def fail_at_tick(self, ticks_ahead: int = 1) -> None:
        """Chaos/operator hook: make the scheduler raise InjectedCrash
        ``ticks_ahead`` busy ticks from now — the replica_flap fault's
        deterministic crash-after-rejoin, also usable as a manual
        replica kill. A real crash in every observable way (failover,
        supervisor respawn ladder, spans)."""
        with self._cv:
            self._die_tick = self._ticks + max(1, int(ticks_ahead))
            self._cv.notify_all()

    # -- KV-block streaming (pod disaggregation, serving/pod.py, ISSUE 19) ---
    def run_on_scheduler(self, fn, timeout: Optional[float] = None):
        """Run ``fn(engine)`` ON the scheduler thread, between ticks, and
        return its result (re-raising its exception). This is the only
        safe way for another thread to touch the donated pool buffers or
        the radix tree: between ticks no jit call is in flight and the
        refcount tables are consistent. Called from the scheduler thread
        itself, runs inline (the warm/export composition)."""
        if threading.current_thread() is self._thread:
            return fn(self)
        call = _HostCall(fn)
        with self._cv:
            self._check_open()
            self._host_calls.append(call)
            self._cv.notify_all()
        if not call.done.wait(timeout):
            raise TimeoutError("scheduler did not service the host call "
                               f"within {timeout}s")
        if call.error is not None:
            raise call.error
        return call.result

    def export_kv_prefix(self, tokens, timeout: Optional[float] = None):
        """Serialize the cached KV blocks covering ``tokens`` — the
        prefill side of disaggregated serving. Matches the radix tree
        (longest cached prefix, capped at len-1 like every splice) and
        gathers the matched pool rows to host memory. Returns ``None``
        when nothing is cached, else a dict with ``matched_len``,
        ``block_size``, ``dtype``, ``shape`` and host-numpy ``kb``/``vb``
        of shape (n_blocks, layers, heads, block_size, head_dim). The
        gather runs on the scheduler thread (:meth:`run_on_scheduler`);
        the returned arrays are copies, safe to ship over RPC."""
        if self._prefix is None:
            raise RuntimeError("export_kv_prefix needs prefix_cache=True")
        toks = np.asarray(tokens, np.int32).reshape(-1)

        def _export(eng):
            m_len, blocks, shard = 0, [], 0
            for d in range(eng.cache.shards):
                m, bl = eng._prefix.match(d, toks)
                if m > m_len:
                    m_len, blocks, shard = m, bl, d
            if m_len <= 0 or not blocks:
                return None
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            kb = np.asarray(jax.device_get(eng.cache.kb[idx]))
            vb = np.asarray(jax.device_get(eng.cache.vb[idx]))
            return {"matched_len": int(m_len),
                    "block_size": int(eng.block_size),
                    "dtype": str(kb.dtype), "shape": list(kb.shape),
                    "kb": kb, "vb": vb}

        return self.run_on_scheduler(_export, timeout=timeout)

    def import_kv_prefix(self, tokens, kb, vb, matched_len: int,
                         timeout: Optional[float] = None) -> int:
        """Splice streamed KV blocks (an :meth:`export_kv_prefix` payload
        from a prefill-role peer) into this engine's pool and radix tree
        — the decode side of disaggregated serving. Best-effort: returns
        the number of tokens now cached for the prefix (0 when the pool
        has no room), after which a plain ``submit`` of the same prompt
        hits the radix tree and splices exactly like a local prefix hit
        — the pinned token-identity guarantee carries over unchanged."""
        if self._prefix is None:
            raise RuntimeError("import_kv_prefix needs prefix_cache=True")
        toks = np.asarray(tokens, np.int32).reshape(-1)[:int(matched_len)]
        kb = np.asarray(kb)
        vb = np.asarray(vb)
        n = int(kb.shape[0])
        if toks.size <= 0 or n == 0:
            return 0
        if n != self.cache.blocks_for(toks.size) or kb.shape != vb.shape:
            raise ValueError(
                f"import_kv_prefix: {n} streamed blocks do not cover "
                f"{toks.size} tokens at block_size {self.block_size}")

        def _import(eng):
            # already warm (idempotent re-stream)? keep the local copy
            have = max(eng._prefix.peek(d, toks)
                       for d in range(eng.cache.shards))
            if have >= toks.size:
                return int(have)
            # target the shard with the most reclaimable room
            best_d, room = 0, -1
            for d in range(eng.cache.shards):
                avail = (eng.cache.free_blocks_of(d)
                         + eng._prefix.evictable_count(d))
                if avail > room:
                    best_d, room = d, avail
            if room < n:
                return 0
            short = n - eng.cache.free_blocks_of(best_d)
            if short > 0 and eng._prefix.evict(best_d, short) < short:
                return 0
            blocks = []
            for _ in range(n):
                b = eng.cache.alloc_block(best_d)
                if b is None:          # lost the race: roll back cleanly
                    for bb in blocks:
                        eng.cache.unref_block(bb)
                    return 0
                blocks.append(b)
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            dt = eng.cache.kb.dtype
            eng.cache.kb = eng.cache.kb.at[idx].set(jnp.asarray(kb, dt))
            eng.cache.vb = eng.cache.vb.at[idx].set(jnp.asarray(vb, dt))
            eng._prefix.insert(best_d, toks, blocks)
            # insert() took a tree reference on every chunk it adopted;
            # drop the alloc-time reference so the tree is sole owner and
            # duplicates of chunks it already held free immediately
            for b in blocks:
                eng.cache.unref_block(b)
            eng.cache.update_gauges()
            return int(eng._prefix.peek(best_d, toks))

        return self.run_on_scheduler(_import, timeout=timeout)

    def export_kv_range(self, tokens, start_block: int,
                        max_blocks: Optional[int] = None,
                        timeout: Optional[float] = None):
        """Incremental slice of :meth:`export_kv_prefix` for resumable
        chunked streaming (ISSUE 20): export only the cached blocks from
        ``start_block`` onward, so finished prefill chunks ship while
        the next chunk computes. While the prefill is still running only
        FULL blocks are exported (a partial tail block would be
        re-written by the next chunk); once the whole prefix is cached
        (``done=True``) the partial tail block ships too. Returns a dict
        with ``matched_len``/``start_block``/``n_blocks``/``done`` plus
        host-numpy ``kb``/``vb`` (possibly 0-length — poll again)."""
        if self._prefix is None:
            raise RuntimeError("export_kv_range needs prefix_cache=True")
        toks = np.asarray(tokens, np.int32).reshape(-1)
        start = int(start_block)

        def _export(eng):
            m_len, blocks, shard = 0, [], 0
            for d in range(eng.cache.shards):
                m, bl = eng._prefix.match(d, toks)
                if m > m_len:
                    m_len, blocks, shard = m, bl, d
            bs = int(eng.block_size)
            # match() caps at len-1 by design, so "whole prefix cached"
            # is m_len >= size-1 — the same terminal every splice uses
            done = m_len >= toks.size - 1
            avail = len(blocks) if done else m_len // bs
            if not done:
                # mid-prefill visibility: the radix insert only happens
                # when the WHOLE prompt is cached, so a slot still
                # prefilling this prompt is invisible to match() — scan
                # live slots and ship their finished FULL blocks while
                # the next chunk computes (the partial tail rides the
                # radix entry once ``done`` flips). Safe: this runs on
                # the scheduler thread between ticks, and a slot's
                # prompt blocks are never rewritten once filled.
                for slot in range(eng.n_slots):
                    st = eng._slots[slot]
                    if st is None:
                        continue
                    pr = np.asarray(st.req.prompt, np.int32).reshape(-1)
                    n_full = min(int(st.length), toks.size) // bs
                    if (n_full > avail and pr.size >= toks.size
                            and np.array_equal(pr[:toks.size], toks)):
                        tbl = eng.cache.block_tables[slot]
                        blocks = [int(b) for b in tbl[:n_full]]
                        avail, m_len = n_full, n_full * bs
            lo = min(start, avail)
            hi = avail if max_blocks is None \
                else min(avail, lo + int(max_blocks))
            out = {"matched_len": int(m_len), "start_block": int(lo),
                   "n_blocks": int(hi - lo), "block_size": bs,
                   "done": bool(done),
                   # prefix tokens covered by blocks [0, hi) — the
                   # n_tokens a receiver passes to import_kv_chunk
                   "covered_tokens": int(min(m_len, hi * bs))}
            if hi > lo:
                idx = jnp.asarray(np.asarray(blocks[lo:hi], np.int32))
                out["kb"] = np.asarray(jax.device_get(eng.cache.kb[idx]))
                out["vb"] = np.asarray(jax.device_get(eng.cache.vb[idx]))
            return out

        return self.run_on_scheduler(_export, timeout=timeout)

    def import_kv_chunk(self, tokens, kb, vb, start_block: int,
                        n_tokens: int,
                        timeout: Optional[float] = None) -> int:
        """Splice ONE streamed chunk (an :meth:`export_kv_range` slice)
        into the pool + radix tree, extending a prefix whose earlier
        blocks were imported by previous chunks. Returns the receiver's
        high-water mark — the number of prefix tokens now cached — which
        is the ack the sender resumes from: a chunk that arrives out of
        order (its ``start_block`` is past what this engine holds) is
        dropped and the current mark returned, so a lost frame rewinds
        the stream instead of corrupting it. Idempotent on re-delivery."""
        if self._prefix is None:
            raise RuntimeError("import_kv_chunk needs prefix_cache=True")
        n_tok = int(n_tokens)
        toks = np.asarray(tokens, np.int32).reshape(-1)[:n_tok]
        kb = np.asarray(kb)
        vb = np.asarray(vb)
        n = int(kb.shape[0])
        start = int(start_block)
        if toks.size != n_tok or n_tok <= 0:
            raise ValueError(f"import_kv_chunk: prompt carries {toks.size} "
                             f"tokens, chunk claims {n_tok}")
        if n == 0 or kb.shape != vb.shape \
                or start + n != self.cache.blocks_for(n_tok):
            raise ValueError(
                f"import_kv_chunk: {n} blocks at {start} do not land on "
                f"{n_tok} tokens at block_size {self.block_size}")

        def _import(eng):
            bs = int(eng.block_size)
            # the shard holding the deepest copy of this prefix is the
            # stream target; its peek is the ack high-water mark
            best_d, have = 0, -1
            for d in range(eng.cache.shards):
                p = eng._prefix.peek(d, toks)
                if p > have:
                    best_d, have = d, p
            if have >= n_tok:
                return int(have)           # idempotent re-delivery
            if have < start * bs:
                return int(have)           # gap: sender must rewind
            _, ex_blocks = eng._prefix.match(best_d, toks)
            room = (eng.cache.free_blocks_of(best_d)
                    + eng._prefix.evictable_count(best_d))
            if room < n:
                return int(have)
            short = n - eng.cache.free_blocks_of(best_d)
            if short > 0 and eng._prefix.evict(best_d, short) < short:
                return int(have)
            blocks = []
            for _ in range(n):
                b = eng.cache.alloc_block(best_d)
                if b is None:
                    for bb in blocks:
                        eng.cache.unref_block(bb)
                    return int(have)
                blocks.append(b)
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            dt = eng.cache.kb.dtype
            eng.cache.kb = eng.cache.kb.at[idx].set(jnp.asarray(kb, dt))
            eng.cache.vb = eng.cache.vb.at[idx].set(jnp.asarray(vb, dt))
            # the first start blocks are the tree's own nodes from the
            # previous chunks — insert() dedupes them by chunk key and
            # only adopts (and refs) the new tail
            eng._prefix.insert(best_d, toks,
                               list(ex_blocks[:start]) + blocks)
            for b in blocks:
                eng.cache.unref_block(b)
            eng.cache.update_gauges()
            return int(eng._prefix.peek(best_d, toks))

        return self.run_on_scheduler(_import, timeout=timeout)

    # -- health surface (EngineRouter / frontend readyz) ---------------------
    @property
    def alive(self) -> bool:
        """Scheduler running and able to make progress."""
        return self._thread.is_alive() and not self._stop \
            and self._error is None

    @property
    def busy(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def tick_age(self) -> float:
        """Seconds since the scheduler last completed a loop iteration
        (fresh even when idle — the idle wait beats every 50ms)."""
        with self._cv:
            return time.monotonic() - self._last_tick_t

    def pool_headroom(self) -> float:
        """Free fraction of the KV capacity (blocks when paged, slots
        otherwise) — the /readyz admission-headroom signal."""
        if self.paged:
            total = self.cache.n_blocks - self.cache.shards
            return self.cache.free_blocks_count / max(1, total)
        return self.cache.free_count / max(1, self.n_slots)

    def generate(self, prompt: Sequence[int] = None, **kw) -> List[int]:
        """Blocking convenience wrapper: submit + result."""
        return self.submit(prompt, **kw).result()

    def rank(self, slots, dense=None):
        """Score a batch of sparse-feature requests against the armed
        embedding tables (``embedding_tables=``): ``slots`` = {name:
        (B, L) int ids}, optional ``dense`` = (B, n_dense) floats.
        Returns (B,) numpy scores. Thread-safe (the lookup runs on the
        caller's thread — it shares no state with the scheduler)."""
        if self._ranker is None:
            raise RuntimeError(
                "ranking not enabled: construct the engine with "
                "embedding_tables= to arm the sparse lookup path")
        return self._ranker.rank(slots, dense=dense)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the scheduler. ``drain=True`` finishes every submitted
        request first; ``drain=False`` evicts them with
        ``finish_reason="shutdown"``."""
        with self._cv:
            self._stop = True
            self._drain = drain
            self._cv.notify_all()
        self._thread.join(timeout)

    @property
    def occupancy(self) -> int:
        return self.cache.occupancy

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- scheduler -----------------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    self._last_tick_t = time.monotonic()
                    if self._evacuate:
                        # lifecycle drain-shrink: fail every open stream
                        # with the adoption-triggering cause (see
                        # evacuate()) — raised here so it runs on the
                        # scheduler thread, never racing a live tick
                        raise ReplicaEvacuated(
                            f"replica {self.replica_id} evacuated "
                            "(drain-shrink)")
                    busy = bool(self._queue) or any(
                        s is not None for s in self._slots)
                    if self._stop and (not self._drain or not busy):
                        break
                    # run_on_scheduler closures (ISSUE 19): popped under
                    # the lock, run outside it — between ticks, so the
                    # pool buffers are quiescent (no donated jit call in
                    # flight) and the radix tree is consistent
                    calls = None
                    if self._host_calls:
                        calls = list(self._host_calls)
                        self._host_calls.clear()
                    if not busy and not calls:
                        self._cv.wait(0.05)
                        continue
                    die = self._die_tick
                if calls:
                    for c in calls:
                        c.run(self)
                    if not busy:
                        continue
                self._ticks += 1
                if die is not None and self._ticks >= die:
                    # fail_at_tick (replica_flap chaos / operator kill):
                    # indistinguishable from a real scheduler crash
                    raise _faults.InjectedCrash(
                        f"injected flap crash (replica {self.replica_id}, "
                        f"tick {self._ticks})")
                if _faults.ENABLED[0]:
                    # serving chaos hooks (tick-keyed, per replica):
                    # slow_tick stalls the scheduler (drives the brownout
                    # EWMA and the watchdog latency rung), replica_crash
                    # kills it (drives router failover)
                    f = _faults.FAULTS.take_tick(
                        "slow_tick", self.replica_id, self._ticks)
                    if f is not None:
                        FAULTS_INJECTED.add()
                        time.sleep(f.secs)
                    f = _faults.FAULTS.take_tick(
                        "replica_crash", self.replica_id, self._ticks)
                    if f is not None:
                        FAULTS_INJECTED.add()
                        raise _faults.InjectedCrash(
                            f"injected replica crash (replica "
                            f"{self.replica_id}, tick {self._ticks})")
                self._admit()
                if self.paged and native.serving_jit[0]:
                    self._prefill_chunk_tick()
                if any(s is not None for s in self._slots):
                    self._decode_tick()
        except BaseException as e:  # noqa: BLE001 — fail every request, not silently
            self._abort(e)
        finally:
            with self._cv:
                self._stop = True
                leftovers = list(self._queue)
                self._queue.clear()
                stranded = list(self._host_calls)
                self._host_calls.clear()
                SERVING_QUEUE_DEPTH.set(0)
                self._cv.notify_all()
            for c in stranded:
                c.fail(RuntimeError(
                    "engine shut down before the host call ran"))
            for req in leftovers:
                req._finish(SHUTDOWN)
            for s, st in enumerate(self._slots):
                if st is not None:
                    self._evict(s, SHUTDOWN)

    def _check_open(self) -> None:
        """Fail fast once the scheduler is gone: nothing will ever drain
        the queue again, so enqueueing would hang the caller forever.
        After a crash the stored cause rides the error so callers see WHY
        the engine died, not just that it is closed."""
        if not self._stop:
            return
        if self._error is not None:
            raise RuntimeError(
                f"InferenceEngine scheduler crashed: "
                f"{type(self._error).__name__}: {self._error}") \
                from self._error
        raise RuntimeError("InferenceEngine is shut down")

    def _abort(self, err: BaseException) -> None:
        # black-box dump at the moment of death: the last ring of spans/
        # gauge deltas, named per host so multi-host dumps merge (no-op
        # when no flight recorder is armed; never raises)
        dump_flight(f"engine_abort_{type(err).__name__}",
                    extra={"replica": self.replica_id,
                           "error": f"{type(err).__name__}: {err}"})
        with self._cv:
            # close the engine BEFORE failing requests so a racing
            # submit() cannot slip into the dead queue
            self._error = err
            self._stop = True
            leftovers = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        for s, st in enumerate(self._slots):
            if st is not None:
                # clear the slot FIRST: a router failover may leave the
                # request unfinished (adopted by a survivor), and the
                # _run finally block must not re-finish it as SHUTDOWN
                self._slots[s] = None
                st.req._finish(ERROR, err)
        for req in leftovers:
            req._finish(ERROR, err)

    def _shed_expired(self) -> None:
        """Shed queued work that can no longer finish — deadline-expired
        or cancelled requests leave the queue at the NEXT tick, before
        any prefill is spent on them, wherever they sit in line (not
        just at the head). The front end maps an empty-handed deadline
        finish to 503 + Retry-After; ``serving_deadline_sheds`` counts
        the sheds so overload_report can tell shed load from served."""
        now = time.monotonic()
        shed: List[GenerationRequest] = []
        with self._cv:
            if not self._queue:
                return
            keep: collections.deque = collections.deque()
            for req in self._queue:
                if req._cancelled or (req.deadline is not None
                                      and now > req.deadline):
                    shed.append(req)
                else:
                    keep.append(req)
            if not shed:
                return
            self._queue = keep
            SERVING_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()   # wake submitters blocked on full
        for req in shed:
            if req._cancelled:
                req._finish(CANCELLED)
            else:
                SERVING_DEADLINE_SHEDS.add(1)
                req._finish(DEADLINE)

    def _admit(self) -> None:
        """Move queued requests into free slots. Fixed mode: prefill-and-
        insert on the spot. Paged mode: capacity-check the head of the
        queue against the free-block pool of a shard that also has a
        free slot (queue-until-available — a too-long prompt waits for
        evictions instead of being rejected; multi-chip admission lands
        in the shard with the most free blocks), then park the prompt on
        the slot for the chunked-prefill tick."""
        self._shed_expired()
        paged = self.paged and native.serving_jit[0]
        while self.cache.free_count > 0:
            shard = None
            place = None
            with self._cv:
                if not self._queue:
                    break
                if paged:
                    head = self._queue[0]
                    seq = head._resume[0] if head._resume is not None \
                        else head.prompt
                    place = self._admit_place(seq)
                    if place is None:
                        break   # head-of-line waits for blocks to free up
                    shard = place[0]
                req = self._queue.popleft()
                SERVING_QUEUE_DEPTH.set(len(self._queue))
                self._cv.notify_all()   # wake submitters blocked on full
            if req._cancelled:
                req._finish(CANCELLED)
                continue
            if req.deadline is not None and time.monotonic() > req.deadline:
                # expired while queued: shed BEFORE spending any prefill
                SERVING_DEADLINE_SHEDS.add(1)
                req._finish(DEADLINE)
                continue
            if req._t_submit:
                wait_ms = (time.monotonic() - req._t_submit) * 1e3
                SERVING_QUEUE_WAIT_MS.observe(wait_ms)
                if self.overload is not None:
                    self.overload.observe_queue_wait(wait_ms)
            slot = self.cache.alloc(prefer_shard=shard) if paged \
                else self.cache.alloc()
            if paged:
                st = _Slot(req, length=0, last_token=-1)
                st.generated = len(req.tokens)   # nonzero on resume
                self._admit_seq += 1
                st.admit_order = self._admit_seq
                if req._resume is not None:
                    seq, st.resume_last = req._resume
                    req._resume = None
                else:
                    seq = req.prompt
                _, m_len, m_blocks = place
                if self._prefix is not None:
                    m_len = self._splice_prefix(slot, m_len, m_blocks)
                    self._prefix.note_lookup(m_len, seq.size)
                if m_len > 0:
                    st.length = m_len
                    st.tail_mode = True
                    self.cache.lengths[slot] = m_len
                st.pending = seq[m_len:]
                self._slots[slot] = st
                continue
            try:
                self._prefill(req, slot)
            except BaseException as e:  # noqa: BLE001
                # mid-admission crash: the request is in neither the
                # queue nor a slot, so _abort would miss it — fail it
                # here before the scheduler unwinds
                if self._slots[slot] is None:
                    self.cache.release(slot)
                req._finish(ERROR, e)
                raise
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)

    def _admit_place(self, seq):
        """Where the head request should land: ``(shard, matched_len,
        matched_blocks)``, or None to queue-until-available.

        Without the prefix cache this is PR-10's most-free-blocks shard
        pick. With it, each eligible shard is scored by the radix match
        its tree offers — a shard only needs free blocks for the
        UNCACHED tail (+1 when the last matched block is partially used
        and must be CoW-duplicated), and LRU tree leaves count toward
        capacity because the scheduler reclaims them before giving up."""
        need_total = int(seq.size) + 1
        if self._prefix is None:
            shard = self.cache.admit_shard(need_total)
            return None if shard is None else (shard, 0, [])
        best = None          # (headroom, shard)
        for d in self.cache.free_slot_shards:
            m_len, m_blocks = self._prefix.match(d, seq)
            need = self.cache.blocks_for(need_total) - len(m_blocks) \
                + (1 if m_len % self.block_size else 0)
            avail = self.cache.free_blocks_of(d) \
                + self._prefix.evictable_count(d)
            if need <= avail and (best is None or avail - need > best[0]):
                best = (avail - need, d)
        if best is None:
            return None
        d = best[1]
        # reclaim LRU leaves to cover the shortfall, then RE-match: the
        # eviction could have clipped the matched path itself (only when
        # the tree is down to this very prefix)
        m_len, m_blocks = self._prefix.match(d, seq)
        need = self.cache.blocks_for(need_total) - len(m_blocks) \
            + (1 if m_len % self.block_size else 0)
        short = need - self.cache.free_blocks_of(d)
        if short > 0:
            self._prefix.evict(d, short)
            m_len, m_blocks = self._prefix.match(d, seq)
            need = self.cache.blocks_for(need_total) - len(m_blocks) \
                + (1 if m_len % self.block_size else 0)
            if need > self.cache.free_blocks_of(d):
                return None
        return d, m_len, m_blocks

    def _splice_prefix(self, slot: int, m_len: int, m_blocks) -> int:
        """Wire a radix match into a fresh slot's table: take one
        reference per matched block, and copy-on-write the last block
        when the match ends mid-block (the slot will write offsets the
        tree's readers must never see change). Returns the matched
        length actually kept (trimmed to the block boundary if the CoW
        allocation loses a race with pool pressure)."""
        if m_len == 0:
            return 0
        self.cache.splice(slot, m_blocks)
        if m_len % self.block_size == 0:
            return m_len
        nb = self.cache.alloc_block(self.cache.shard_of(slot))
        if nb is None:
            # no block for the copy: drop the partial block from the
            # match instead (its full-block prefix is still shared)
            self.cache.block_tables[slot].pop()
            self.cache.unref_block(m_blocks[-1])
            return (m_len // self.block_size) * self.block_size
        src = int(m_blocks[-1])
        self.cache.kb, self.cache.vb = self._cow_jit(
            self.cache.kb, self.cache.vb, np.int32(src), np.int32(nb))
        self.cache.replace_block(slot, len(m_blocks) - 1, nb)
        PREFIX_COW_COPIES.add(1)
        return m_len

    def _reclaim_blocks(self, slot: int, n_tokens: int) -> bool:
        """Try to make ``grow(slot, n_tokens)`` succeed by evicting LRU
        prefix-tree leaves from the slot's shard — the reclaim step that
        runs BEFORE youngest-first preemption ever fires."""
        if self._prefix is None:
            return False
        shard = self.cache.shard_of(slot)
        missing = self.cache.blocks_for(n_tokens) \
            - len(self.cache.block_tables[slot]) \
            - self.cache.free_blocks_of(shard)
        if missing <= 0:
            return True
        return self._prefix.evict(shard, missing) >= missing

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _width_bucket(self, n_blocks: int) -> int:
        b = 1
        while b < n_blocks:
            b *= 2
        return min(b, self.cache.table_width)

    def _stream_key(self, rid: int, draw: int):
        """Host-side stream key for single-row programs (prefill): the
        same (seed, request, draw) fold the batched steps compute
        in-jit."""
        return jax.random.fold_in(
            jax.random.fold_in(self._base_key, rid % (2**31 - 1)), draw)

    def _mask_row(self, req: GenerationRequest) -> np.ndarray:
        """(1, V) bool sampling mask for one request's next token —
        all-true when unconstrained, the automaton's live-token set
        (padded to the model vocab) otherwise."""
        if req.constraint is None:
            return self._ones_mask[:1]
        m = req.constraint.mask()
        if m.shape[0] == self.cfg.vocab_size:
            return m[None]
        out = np.zeros((1, self.cfg.vocab_size), bool)
        out[0, :m.shape[0]] = m
        return out

    def _prefill(self, req: GenerationRequest, slot: int) -> None:
        # a watchdog restart requeues fixed-mode streams with a resume
        # record: re-prefill prompt+generated[:-1] and rebuild decode
        # state without re-emitting — the paged preemption-resume
        # contract on the fixed cache
        resume = req._resume
        req._resume = None
        seq = resume[0] if resume is not None else req.prompt
        S = int(seq.size)
        if resume is not None and S + 1 > self.max_len:
            self.cache.release(slot)
            req._finish(LENGTH)
            return
        t0 = time.perf_counter()
        pf_args = {"slot": slot, "prompt_len": S}
        flow = None
        if req.trace is not None and recording():
            pf_args.update(req.trace.args(rid=req.rid))
            flow = req.trace.trace_id
        with span("serving.prefill", cat="serving", args=pf_args,
                  flow=flow):
            if native.serving_jit[0]:
                s_pad = self._bucket(S)
                toks = np.zeros((1, s_pad), np.int32)
                toks[0, :S] = seq
                key = self._stream_key(req.rid, 0)
                if self.draft is not None:
                    (tok, self.cache.k, self.cache.v, self.draft_cache.k,
                     self.draft_cache.v) = self._prefill_spec_jit(
                        self._params, self._draft_params, self.cache.k,
                        self.cache.v, self.draft_cache.k,
                        self.draft_cache.v, jnp.asarray(toks),
                        np.int32(slot), np.int32(S), key,
                        np.float32(req.temperature), np.int32(req.top_k),
                        np.float32(req.top_p),
                        jnp.asarray(self._mask_row(req)))
                else:
                    tok, self.cache.k, self.cache.v = self._prefill_jit(
                        self._params, self.cache.k, self.cache.v,
                        jnp.asarray(toks), np.int32(slot), np.int32(S),
                        key, np.float32(req.temperature),
                        np.int32(req.top_k), np.float32(req.top_p),
                        jnp.asarray(self._mask_row(req)))
            else:
                logits = gpt_forward(self.cfg, self._params,
                                     jnp.asarray(seq[None]))
                tok = sample_tokens(
                    logits[:, -1], self._stream_key(req.rid, 0),
                    jnp.float32(req.temperature)[None],
                    jnp.int32(req.top_k)[None],
                    jnp.float32(req.top_p)[None],
                    mask=jnp.asarray(self._mask_row(req)))[0]
            tok = int(tok)
        pf_ms = (time.perf_counter() - t0) * 1e3
        self._note_ms(SERVING_PREFILL_MS, "_prefill_ms", pf_ms)
        SERVING_PREFILL_CHUNK_MS.observe(pf_ms)
        st = _Slot(req, length=S, last_token=tok)
        self._slots[slot] = st
        self.cache.lengths[slot] = S
        if resume is not None:
            # tokens through resume[1] were already streamed before the
            # restart — rebuild decode state, emit nothing
            st.last_token = resume[1]
            st.generated = len(req.tokens)
            return
        req._push(tok)
        self._note_tokens(1)
        reason = self._finish_reason(st, tok)
        if reason is not None:
            self._evict(slot, reason)

    # -- paged mode: chunked prefill + preemption ----------------------------
    def _open_decode_streams(self) -> int:
        return sum(1 for st in self._slots
                   if st is not None and st.pending is None)

    def _prefill_chunk_tick(self) -> None:
        """Advance every mid-prefill slot by at most one prefill_chunk —
        the decode tick follows in the same scheduler iteration, so open
        streams never wait more than a chunk's work per tick."""
        for slot in range(self.n_slots):
            st = self._slots[slot]
            if st is None or st.pending is None:
                continue
            if st.req._cancelled:
                self._evict(slot, CANCELLED)
            elif st.req.deadline is not None \
                    and time.monotonic() > st.req.deadline:
                self._evict(slot, DEADLINE)
            else:
                self._prefill_one_chunk(slot, st)

    def _prefill_one_chunk(self, slot: int, st: _Slot) -> None:
        pending = st.pending
        chunk_cap = self.prefill_chunk
        if self.overload is not None:
            # brownout rung 2: shrink chunks so long prompts yield the
            # scheduler to open streams more often (re-rounded to the
            # block size, floored at one block)
            chunk_cap = max(self.block_size,
                            (self.overload.prefill_chunk(chunk_cap)
                             // self.block_size) * self.block_size)
        c_true = min(int(pending.size), chunk_cap)
        bs = self.block_size
        c_pad = -(-c_true // bs) * bs    # one compile per padded length
        if st.tail_mode:
            # prefix-matched slots continue from an UNALIGNED length;
            # clamp the pad so scatter positions never run past the
            # table (near the seq_len cap the pad is trimmed odd — a
            # rare extra compile, not a corruption)
            c_pad = min(c_pad, self.cache.table_width * bs - st.length)
        while not self.cache.grow(slot, st.length + c_pad):
            # pool exhausted: reclaim LRU prefix-tree leaves first, then
            # preempt strictly-younger work, else wait for an eviction
            # (the oldest slot is never preempted, so the engine always
            # makes progress — no preemption livelock)
            if self._reclaim_blocks(slot, st.length + c_pad):
                continue
            victim = self._youngest_slot(exclude=slot)
            if victim is None \
                    or self._slots[victim].admit_order <= st.admit_order:
                return
            self._preempt(victim)
        last = c_true == pending.size
        t0 = time.perf_counter()
        ck_args = {"slot": slot, "start": st.length, "chunk": c_true,
                   "tick": self._ticks,
                   "open_streams": self._open_decode_streams()}
        flow = None
        if st.req.trace is not None and recording():
            ck_args.update(st.req.trace.args(rid=st.req.rid))
            flow = st.req.trace.trace_id
        with span("serving.prefill_chunk", cat="serving", args=ck_args,
                  flow=flow):
            toks = np.zeros((1, c_pad), np.int32)
            toks[0, :c_true] = pending[:c_true]
            row = self.cache.table_row(slot)[:self._width_bucket(
                self.cache.blocks_for(st.length + c_pad))]
            if st.tail_mode:
                logits, self.cache.kb, self.cache.vb = self._tail_jit(
                    self._params, self.cache.kb, self.cache.vb,
                    jnp.asarray(row), jnp.asarray(toks),
                    np.int32(st.length))
            elif self.draft is not None:
                (logits, self.cache.kb, self.cache.vb, self.draft_cache.k,
                 self.draft_cache.v) = self._chunk_spec_jit(
                    self._params, self._draft_params, self.cache.kb,
                    self.cache.vb, self.draft_cache.k, self.draft_cache.v,
                    jnp.asarray(row), np.int32(slot), jnp.asarray(toks),
                    np.int32(st.length))
            else:
                logits, self.cache.kb, self.cache.vb = self._chunk_jit(
                    self._params, self.cache.kb, self.cache.vb,
                    jnp.asarray(row), jnp.asarray(toks),
                    np.int32(st.length))
        ck_ms = (time.perf_counter() - t0) * 1e3
        self._note_ms(SERVING_PREFILL_MS, "_prefill_ms", ck_ms)
        SERVING_PREFILL_CHUNK_MS.observe(ck_ms)
        st.length += c_true
        self.cache.lengths[slot] = st.length
        st.pending = None if last else pending[c_true:]
        self.cache.update_gauges()
        if not last:
            return
        if self._prefix is not None and st.length >= st.req.prompt.size:
            # the whole prompt is cached now — register it so the NEXT
            # identical prefix splices these blocks instead of computing
            self._prefix.insert(self.cache.shard_of(slot), st.req.prompt,
                                self.cache.block_tables[slot])
        if st.resume_last is not None:
            # resumed after preemption: the "next" token was already
            # streamed before the preemption — just rebuild decode state
            st.last_token = st.resume_last
            st.resume_last = None
            return
        tok = int(sample_tokens(
            logits[0:1, c_true - 1], self._stream_key(st.req.rid, 0),
            jnp.float32(st.req.temperature)[None],
            jnp.int32(st.req.top_k)[None],
            jnp.float32(st.req.top_p)[None],
            mask=jnp.asarray(self._mask_row(st.req)))[0])
        st.last_token = tok
        st.generated = 1
        st.req._push(tok)
        self._note_tokens(1)
        reason = self._finish_reason(st, tok)
        if reason is not None:
            self._evict(slot, reason)

    def _youngest_slot(self, exclude: int) -> Optional[int]:
        best = None
        for s, st in enumerate(self._slots):
            if st is None or s == exclude:
                continue
            if best is None \
                    or st.admit_order > self._slots[best].admit_order:
                best = s
        return best

    def _preempt(self, slot: int) -> None:
        """Return a slot's blocks to the pool and its request to the HEAD
        of the queue; it resumes later by re-prefilling prompt+generated
        (recompute preemption — tokens already streamed are unaffected)."""
        st = self._slots[slot]
        self._slots[slot] = None
        self.cache.release(slot)
        SERVING_PREEMPTIONS.add(1)
        if st.req.tokens:
            # decode state: cache held prompt + tokens[:-1]; tokens[-1] is
            # the next decode input
            seq = np.concatenate(
                [st.req.prompt,
                 np.asarray(st.req.tokens[:-1], np.int32)]).astype(np.int32)
            st.req._resume = (seq, int(st.req.tokens[-1]))
        else:
            st.req._resume = None       # mid-prefill: just start over
        with self._cv:
            self._queue.appendleft(st.req)
            SERVING_QUEUE_DEPTH.set(len(self._queue))
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)

    def _grow_for_decode(self, active: List[int]) -> List[int]:
        """Ensure each decoding slot's table covers its next write
        position, preempting the youngest slot when the pool runs dry.
        Oldest slots get blocks first (FIFO fairness)."""
        ready = []
        for s in sorted(active, key=lambda s: self._slots[s].admit_order):
            st = self._slots[s]
            if st is None:       # preempted as a victim earlier this tick
                continue
            while not self.cache.grow(s, st.length + 1):
                if self._reclaim_blocks(s, st.length + 1):
                    continue
                victim = self._youngest_slot(exclude=s)
                if victim is None:
                    # alone and the pool is spent: nothing will ever free
                    # a block — cache capacity reached, same terminal
                    # condition as the fixed engine's full slot
                    self._evict(s, LENGTH)
                    break
                if self._slots[victim].admit_order <= st.admit_order:
                    break        # only younger work is preemptible: stall
                self._preempt(victim)
            else:
                ready.append(s)
        return [s for s in ready if self._slots[s] is not None]

    def _try_spec_grow(self, active: List[int]) -> bool:
        """Paged spec headroom: grow every active table to cover the k
        proposals + bonus WITHOUT preempting anyone (speculation is an
        optimization, never worth evicting work for). False → this tick
        falls back to the plain one-token program."""
        for s in active:
            st = self._slots[s]
            if not self.cache.grow(s, st.length + self.spec_k + 1):
                return False
        return True

    def _shard_load(self, active: List[int]) -> List[int]:
        per = self.n_slots // self._shards
        load = [0] * self._shards
        for s in active:
            load[s // per] += 1
        return load

    def _decode_tick(self) -> None:
        now = time.monotonic()
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req._cancelled:
                self._evict(s, CANCELLED)
            elif st.req.deadline is not None and now > st.req.deadline:
                self._evict(s, DEADLINE)
        active = [s for s in range(self.n_slots)
                  if self._slots[s] is not None
                  and self._slots[s].pending is None]
        if not active:
            return
        # speculation needs k+1 positions of cache headroom on every
        # active slot; a near-cap slot drops the whole tick to the plain
        # one-token program (correct, just unaccelerated) rather than
        # splitting the batch across two programs. Constrained rows
        # force the same fallback: draft proposals are not mask-aware,
        # so speculating through an automaton would emit illegal tokens.
        constrained = [s for s in active
                       if self._slots[s].req.constraint is not None]
        use_spec = (self.draft is not None and native.serving_jit[0]
                    and (self.overload is None
                         or self.overload.spec_allowed())
                    and all(self._slots[s].length + self.spec_k + 1
                            <= self.max_len for s in active))
        if use_spec and constrained:
            use_spec = False
            CONSTRAINED_FALLBACK_TICKS.add(1)
        if self.paged and native.serving_jit[0]:
            if use_spec:
                use_spec = self._try_spec_grow(active)
            if not use_spec:
                active = self._grow_for_decode(active)
                if not active:
                    return

        if _faults.ENABLED[0]:
            # serving_nan fault (FLAGS_fault_inject, keyed by REQUEST id):
            # NaN the slot's cached K/V — the deterministic stand-in for
            # poisoned HBM — so the watchdog path is testable on CPU
            for s in active:
                f = _faults.FAULTS.take_request("serving_nan",
                                               self._slots[s].req.rid)
                if f is not None:
                    FAULTS_INJECTED.add()
                    self._poison_slot(s)

        positions = np.zeros(self.n_slots, np.int32)
        tokens = np.zeros(self.n_slots, np.int32)
        temps = np.zeros(self.n_slots, np.float32)
        top_ks = np.zeros(self.n_slots, np.int32)
        top_ps = np.ones(self.n_slots, np.float32)
        rids = np.zeros(self.n_slots, np.int32)
        steps = np.zeros(self.n_slots, np.int32)
        for s in active:
            st = self._slots[s]
            positions[s] = st.length
            tokens[s] = st.last_token
            temps[s] = st.req.temperature
            top_ks[s] = st.req.top_k
            top_ps[s] = st.req.top_p
            rids[s] = st.req.rid % (2**31 - 1)
            steps[s] = len(st.req.tokens)
        # per-slot sampling mask: the device-resident all-true buffer on
        # unconstrained ticks (no per-tick transfer), a fresh host array
        # carrying each constrained row's automaton mask otherwise
        if constrained:
            masks = self._ones_mask.copy()
            for s in constrained:
                masks[s] = self._mask_row(self._slots[s].req)[0]
            mask_arg = jnp.asarray(masks)
        else:
            mask_arg = self._mask_dev

        span_args = {"batch": len(active), "tick": self._ticks}
        if self.replica_id is not None:
            span_args["replica"] = self.replica_id
        if self._shards > 1:
            span_args["shards"] = self._shards
            span_args["shard_load"] = self._shard_load(active)
        if use_spec:
            span_args["spec_k"] = self.spec_k
        t0 = time.perf_counter()
        health = None
        # span_args is serialized when the span closes, so the spec
        # proposed/accepted counts added below land in the trace event
        with span("serving.decode_step", cat="serving", args=span_args):
            if use_spec:
                out, n_emit, health = self._spec_dispatch(
                    active, positions, tokens, rids, steps, temps,
                    top_ks, top_ps)
            elif native.serving_jit[0]:
                if self.paged:
                    # table width bucketed to the live maximum (next pow2):
                    # attention/gather work tracks LIVE tokens, not the
                    # worst-case table — one compile per width bucket,
                    # log2(table_width) programs total
                    tables = self.cache.tables_array(active)
                    tables = tables[:, :self._width_bucket(
                        max(len(self.cache.block_tables[s])
                            for s in active))]
                    got = self._decode_paged_jit(
                        self._decode_params, self.cache.kb,
                        self.cache.vb, tables, positions, tokens,
                        self._base_key, rids, steps, temps, top_ks,
                        top_ps, mask_arg)
                    moe_stats = None
                    if self._moe:
                        *got, moe_stats = got
                    if self._watchdog is not None:
                        out, health, self.cache.kb, self.cache.vb = got
                    else:
                        out, self.cache.kb, self.cache.vb = got
                else:
                    got = self._decode_jit(
                        self._decode_params, self.cache.k, self.cache.v,
                        positions, tokens, self._base_key, rids, steps,
                        temps, top_ks, top_ps, mask_arg)
                    moe_stats = None
                    if self._moe:
                        *got, moe_stats = got
                    if self._watchdog is not None:
                        out, health, self.cache.k, self.cache.v = got
                    else:
                        out, self.cache.k, self.cache.v = got
                out = np.asarray(out)
                n_emit = None
                if moe_stats is not None:
                    self._note_moe(moe_stats, span_args)
            else:
                # reference decode: full recompute per sequence, no cache
                out = np.zeros(self.n_slots, np.int32)
                if self._watchdog is not None:
                    health = np.ones(self.n_slots, bool)
                for s in active:
                    st = self._slots[s]
                    seq = np.concatenate(
                        [st.req.prompt, np.asarray(st.req.tokens, np.int32)])
                    logits = gpt_forward(self.cfg, self._params,
                                         jnp.asarray(seq[None]))
                    if health is not None:
                        health[s] = bool(np.all(np.isfinite(
                            np.asarray(logits[:, -1]))))
                    out[s] = int(sample_tokens(
                        logits[:, -1],
                        self._stream_key(int(rids[s]), int(steps[s])),
                        temps[s:s + 1], top_ks[s:s + 1], top_ps[s:s + 1],
                        mask=jnp.asarray(self._mask_row(st.req)))[0])
                n_emit = None
            if use_spec:
                span_args["proposed"] = self.spec_k * len(active)
                span_args["accepted"] = int(sum(int(n_emit[s]) - 1
                                               for s in active))
        tick_ms = (time.perf_counter() - t0) * 1e3
        self._note_ms(SERVING_DECODE_MS, "_decode_ms", tick_ms)
        SERVING_DECODE_TICK_MS.observe(tick_ms)
        if self.overload is not None:
            self.overload.observe_tick(tick_ms)
        if self._watchdog is not None:
            poisoned = [] if health is None else \
                [s for s in active if not bool(np.asarray(health)[s])]
            if poisoned:
                SERVING_WATCHDOG_TRIPS.add(len(poisoned))
                # the whole tick's outputs are dropped: poisoned streams
                # fail, healthy ones resume by replay — token-identical,
                # the same exactness contract as preemption-resume
                self._watchdog_restart(poisoned)
                return
            self._watchdog_latency(tick_ms)

        emitted = 0
        traced = []       # (req, tokens pushed) for per-request tick events
        for s in active:
            st = self._slots[s]
            burst = [int(out[s])] if n_emit is None \
                else [int(t) for t in out[s, :int(n_emit[s])]]
            pushed = 0
            for tok in burst:
                st.length += 1
                st.generated += 1
                st.last_token = tok
                self.cache.lengths[s] = st.length
                st.req._push(tok)
                emitted += 1
                pushed += 1
                reason = self._finish_reason(st, tok)
                if reason is not None:
                    self._evict(s, reason)
                    break
            if st.req.trace is not None:
                traced.append((st.req, pushed))
        if traced and recording():
            # one per-request decode-tick event per traced participant:
            # the causal twin of the BATCHED serving.decode_step span,
            # letting request_report/chrome attribute this tick's time
            # to each request riding it (gated — no cost untraced)
            dur = tick_ms / 1e3
            for req, n_toks in traced:
                rq_args = req.trace.args(rid=req.rid, tokens=n_toks,
                                         tick=self._ticks)
                if self.replica_id is not None:
                    rq_args["replica"] = self.replica_id
                emit_complete("serving.decode_tick", t0, dur,
                              cat="serving", args=rq_args)
                emit_flow("t", req.trace.trace_id, t0)
        if use_spec:
            self._note_spec(self.spec_k * len(active),
                            int(sum(int(n_emit[s]) - 1 for s in active)))
        self._note_tokens(emitted)
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)
        if self.paged:
            self.cache.update_gauges()   # refresh kv_fragmentation vs lengths

    def _spec_dispatch(self, active, positions, tokens, rids, steps, temps,
                       top_ks, top_ps):
        """Run the one-program speculative tick: draft proposes spec_k,
        target verifies k+1 positions, rejection sampling accepts.
        Returns (out_tokens (B, k+1) np, n_emit (B,) np, health (B,) np
        or None) — health only when the watchdog is armed, computed over
        every verify position inside the same compiled program."""
        health = None
        if self.paged:
            tables = self.cache.tables_array(active)
            tables = tables[:, :self._width_bucket(
                max(len(self.cache.block_tables[s]) for s in active))]
            got = self._spec_paged_jit(
                self._decode_params, self._draft_params, self.cache.kb,
                self.cache.vb, self.draft_cache.k, self.draft_cache.v,
                tables, positions, tokens, self._base_key, rids, steps,
                temps, top_ks, top_ps)
            if self._watchdog is not None:
                (out, n_emit, health, self.cache.kb, self.cache.vb,
                 self.draft_cache.k, self.draft_cache.v) = got
            else:
                (out, n_emit, self.cache.kb, self.cache.vb,
                 self.draft_cache.k, self.draft_cache.v) = got
        else:
            got = self._spec_jit(
                self._decode_params, self._draft_params, self.cache.k,
                self.cache.v, self.draft_cache.k, self.draft_cache.v,
                positions, tokens, self._base_key, rids, steps, temps,
                top_ks, top_ps)
            if self._watchdog is not None:
                (out, n_emit, health, self.cache.k, self.cache.v,
                 self.draft_cache.k, self.draft_cache.v) = got
            else:
                (out, n_emit, self.cache.k, self.cache.v,
                 self.draft_cache.k, self.draft_cache.v) = got
        return (np.asarray(out), np.asarray(n_emit),
                None if health is None else np.asarray(health))

    def _finish_reason(self, st: _Slot, tok: int) -> Optional[str]:
        """Why generation stops after emitting ``tok`` (None = keep
        going). Called exactly once per emitted token, so this is also
        where a constrained request's automaton consumes the token."""
        if st.req.eos_id is not None and tok == st.req.eos_id:
            return EOS
        if st.req.constraint is not None:
            alive = st.req.constraint.advance(tok)
            if st.req.constraint.finished or not alive:
                return STOP    # match complete (or an unmasked escape-
                #                hatch token killed it) — stream is done
        if st.generated >= st.req.max_new_tokens:
            return LENGTH
        if st.length >= self.max_len:
            return LENGTH      # cache slot full — nothing further fits
        return None

    def _evict(self, slot: int, reason: str) -> None:
        st = self._slots[slot]
        self._slots[slot] = None
        self.cache.release(slot)
        SERVING_EVICTIONS.add(1)
        SERVING_SLOT_OCCUPANCY.set(self.cache.occupancy)
        st.req._finish(reason)

    # -- watchdog: NaN/latency sentinel + auto-restart -----------------------
    def _poison_slot(self, slot: int) -> None:
        """serving_nan fault effect: overwrite the slot's cached K/V rows
        with NaN (the deterministic stand-in for poisoned HBM / a bad
        collective). Only the jitted cache-decode paths read these rows —
        the FLAGS_serving_jit=0 reference decode recomputes from tokens
        and never sees them."""
        nan = float("nan")
        if self.paged:
            rows = jnp.asarray(self.cache.block_tables[slot], jnp.int32)
            self.cache.kb = self.cache.kb.at[rows].set(nan)
            self.cache.vb = self.cache.vb.at[rows].set(nan)
        else:
            self.cache.k = self.cache.k.at[slot].set(nan)
            self.cache.v = self.cache.v.at[slot].set(nan)

    def _watchdog_latency(self, tick_ms: float) -> None:
        """Latency rung of the sentinel: ``latency_trips`` consecutive
        decode ticks over ``latency_budget_ms`` is a stall verdict —
        counted and timestamped for the trace, not restarted (a restart
        cannot make compute faster; an operator can)."""
        budget = self._watchdog["latency_budget_ms"]
        if not budget:
            return
        if tick_ms <= float(budget):
            self._slow_ticks = 0
            return
        self._slow_ticks += 1
        if self._slow_ticks >= int(self._watchdog["latency_trips"]):
            self._slow_ticks = 0
            SERVING_WATCHDOG_TRIPS.add()
            if recording():
                emit_instant("serving.watchdog_stall", time.perf_counter(),
                             cat="serving")

    def _watchdog_restart(self, poisoned: List[int]) -> None:
        """Engine auto-restart from the last healthy state: fail ONLY the
        poisoned requests, requeue every healthy open stream with its
        token history (admission replays it through the preemption-resume
        path — continuations are token-identical because the per-request
        RNG streams are pure functions of (seed, rid, draw)), and rebuild
        the device cache + prefix tree from scratch — the old pool may
        hold NaN rows behind shared blocks or the garbage sink."""
        self._restarts += 1
        if self._restarts > int(self._watchdog["max_restarts"]):
            # the last rung: a persistently-poisoned engine fails loudly
            # (scheduler _abort fails every open request with this cause;
            # _abort also writes the flight dump)
            raise WatchdogTripped(
                f"watchdog restart budget exhausted "
                f"(max_restarts={self._watchdog['max_restarts']})")
        SERVING_WATCHDOG_RESTARTS.add()
        dump_flight("serving_watchdog_restart",
                    extra={"replica": self.replica_id,
                           "poisoned": sorted(poisoned),
                           "restart": self._restarts})
        bad = set(poisoned)
        healthy = sorted(
            ((st.admit_order, s) for s, st in enumerate(self._slots)
             if st is not None and s not in bad), reverse=True)
        with span("serving.watchdog_restart", cat="serving",
                  args={"poisoned": sorted(bad), "healthy": len(healthy),
                        "restart": self._restarts, "tick": self._ticks}):
            for s in bad:
                st = self._slots[s]
                self._slots[s] = None
                SERVING_EVICTIONS.add(1)
                st.req._finish(WATCHDOG, WatchdogTripped(
                    f"non-finite decode logits (request {st.req.rid})"))
            # youngest first through appendleft => oldest ends up at the
            # queue head, preserving admission order on replay
            for _, s in healthy:
                st = self._slots[s]
                self._slots[s] = None
                if st.req.tokens:
                    seq = np.concatenate(
                        [st.req.prompt,
                         np.asarray(st.req.tokens[:-1],
                                    np.int32)]).astype(np.int32)
                    st.req._resume = (seq, int(st.req.tokens[-1]))
                else:
                    st.req._resume = None   # mid-prefill: just start over
                with self._cv:
                    self._queue.appendleft(st.req)
            self._reset_cache()
        with self._cv:
            SERVING_QUEUE_DEPTH.set(len(self._queue))
        SERVING_SLOT_OCCUPANCY.set(0)

    def _reset_cache(self) -> None:
        """Fresh zeroed cache buffers + accounting (and a fresh prefix
        tree — cached prefixes may reference poisoned blocks; dropping
        the cache costs recompute, never correctness)."""
        max_len, n_blocks, block_size = self._cache_args
        if self.paged:
            self.cache = PagedKVCache(self.cfg, self.n_slots,
                                      n_blocks=n_blocks,
                                      block_size=block_size,
                                      shards=self._shards)
            if self._mesh is not None:
                self.cache.kb = self._put_cache(self.cache.kb)
                self.cache.vb = self._put_cache(self.cache.vb)
        else:
            self.cache = KVCache(self.cfg, self.n_slots, max_len)
            if self._mesh is not None:
                self.cache.k = self._put_cache(self.cache.k)
                self.cache.v = self._put_cache(self.cache.v)
        if self._prefix is not None:
            self._prefix = RadixPrefixCache(self.cache)
        if self.draft is not None:
            # the draft's K/V were computed alongside the poisoned
            # target rows — rebuild its fixed cache too, so the spec
            # path resumes from the same clean slate (ISSUE 14)
            self.draft_cache = self._build_draft_cache()
        if hasattr(self.cache, "update_gauges"):
            self.cache.update_gauges()

    # -- gauges --------------------------------------------------------------
    def _note_moe(self, moe_stats, span_args=None) -> None:
        """Publish per-tick router stats: busiest-expert share (ppm
        gauge + per-expert % histogram — the spread IS the imbalance)
        and the cumulative dropped-assignment counter. Decode is
        dropless (C=T), so dropped stays 0 there; the counter exists
        for parity with training capacity accounting."""
        counts, dropped = moe_stats
        counts = np.asarray(counts, np.int64)
        total = int(counts.sum())
        if total > 0:
            shares = counts / total
            MOE_EXPERT_LOAD.set(int(float(shares.max()) * 1e6))
            for sh in shares:
                MOE_EXPERT_SHARE_PCT.observe(float(sh) * 100.0)
        nd = int(np.asarray(dropped))
        if nd:
            MOE_TOKENS_DROPPED.add(nd)
        if span_args is not None and total > 0:
            span_args["moe_busiest_pct"] = round(
                float(counts.max()) / total * 100.0, 2)
            span_args["moe_dropped"] = nd

    def _note_ms(self, gauge, attr: str, ms: float) -> None:
        old = getattr(self, attr)
        new = old + ms
        setattr(self, attr, new)
        gauge.add(int(new) - int(old))

    def _note_spec(self, proposed: int, accepted: int) -> None:
        SPEC_PROPOSED.add(proposed)
        SPEC_ACCEPTED.add(accepted)
        self._spec_prop += proposed
        self._spec_acc += accepted
        if self._spec_prop > 0:
            SPEC_ACCEPTANCE_RATE.set(
                int(round(100.0 * self._spec_acc / self._spec_prop)))

    def _note_tokens(self, n: int) -> None:
        # sliding window over the last N tick completions (deque maxlen):
        # the gauge tracks RECENT rate, so a load spike or an idle dip is
        # visible in trace reports instead of being flattened into a
        # lifetime average
        now = time.monotonic()
        self._window.append((now, n))
        window_span = now - self._window[0][0]
        if len(self._window) >= 2 and window_span > 0:
            total = sum(c for _, c in self._window)
            SERVING_TOKENS_PER_S.set(max(1, int(total / window_span)))
