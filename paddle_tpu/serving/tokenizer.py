"""Byte-level tokenizer front end for the serving engine (ISSUE 10,
stage (e) of the pod-scale serving tentpole).

The engine speaks int32 token ids; this module is the minimal text
boundary in front of it:

- :class:`ByteTokenizer` — ids 0..255 are the raw bytes of the utf-8
  encoding (every string round-trips by construction, no OOV), followed
  by special tokens (``<|eos|>`` by default) and, optionally,
  multi-byte MERGE tokens loaded from a vocab file. Encoding is greedy
  longest-match over the byte string, so a merge vocab compresses
  common sequences while the byte floor guarantees totality — the
  GPT-2/BPE shape without requiring a trained merge table.
- :class:`StreamDetokenizer` — incremental decoding for
  ``GenerationRequest.stream_text()``: emitted bytes are buffered until
  they form complete utf-8 sequences, so a multi-byte character split
  across two generated tokens never renders as replacement garbage.

Vocab files: JSON ``{"tokens": ["ab", ...], "specials": ["<|eos|>"]}``
or a plain text file with one token per line (lines become merge
tokens; escape bytes as ``\\xNN``). ``save()`` writes the JSON form.
Merge/special ids start at 256 in file order, so a vocab file is a
stable contract between the engine that served and the client that
decodes.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["ByteTokenizer", "StreamDetokenizer"]

_N_BYTES = 256


def _to_bytes(tok: Union[str, bytes]) -> bytes:
    return tok.encode("utf-8") if isinstance(tok, str) else bytes(tok)


class ByteTokenizer:
    """Byte-floor tokenizer with optional merge vocab and specials.

    ::

        tok = ByteTokenizer()                       # pure bytes + <|eos|>
        tok = ByteTokenizer(merges=["the ", "ing"]) # with merge tokens
        tok = ByteTokenizer.load("vocab.json")      # from a vocab file

        ids = tok.encode("hello")          # np.int32 (5,)
        tok.decode(ids)                    # "hello"

    Ids 0..255 are the raw bytes; merge tokens and specials follow.
    ``encode`` is greedy longest-match (merge tokens first, byte
    fallback always succeeds); specials are never produced by
    ``encode`` — they are control ids (``eos_id``) the engine emits and
    ``decode(skip_special=True)`` drops.
    """

    def __init__(self, merges: Optional[Sequence[Union[str, bytes]]] = None,
                 specials: Optional[Sequence[str]] = None):
        self.merges: List[bytes] = [_to_bytes(m) for m in (merges or [])]
        for m in self.merges:
            if len(m) < 2:
                raise ValueError(f"merge token {m!r} shorter than 2 bytes "
                                 "(single bytes are the built-in floor)")
        if len(set(self.merges)) != len(self.merges):
            raise ValueError("duplicate merge tokens in vocab")
        self.specials: List[str] = list(specials) if specials is not None \
            else ["<|eos|>"]
        # merge ids follow the byte floor, specials follow the merges —
        # file order is id order, the stable client contract
        self._merge_ids = {m: _N_BYTES + i for i, m in enumerate(self.merges)}
        self._special_ids = {s: _N_BYTES + len(self.merges) + i
                             for i, s in enumerate(self.specials)}
        self._max_merge = max((len(m) for m in self.merges), default=1)

    # -- core ----------------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return _N_BYTES + len(self.merges) + len(self.specials)

    @property
    def eos_id(self) -> Optional[int]:
        return self._special_ids.get("<|eos|>")

    def special_id(self, token: str) -> int:
        return self._special_ids[token]

    def encode(self, text: str) -> np.ndarray:
        """Greedy longest-match over the utf-8 bytes → int32 ids."""
        data = text.encode("utf-8")
        out: List[int] = []
        i, n = 0, len(data)
        while i < n:
            match = None
            for ln in range(min(self._max_merge, n - i), 1, -1):
                tid = self._merge_ids.get(data[i:i + ln])
                if tid is not None:
                    match = (tid, ln)
                    break
            if match is None:
                out.append(data[i])
                i += 1
            else:
                out.append(match[0])
                i += match[1]
        return np.asarray(out, np.int32)

    def token_bytes(self, tid: int) -> Optional[bytes]:
        """The byte expansion of one id; None for specials/out-of-vocab
        (callers skip those)."""
        if 0 <= tid < _N_BYTES:
            return bytes([tid])
        if _N_BYTES <= tid < _N_BYTES + len(self.merges):
            return self.merges[tid - _N_BYTES]
        return None

    def decode(self, ids, skip_special: bool = True) -> str:
        buf = bytearray()
        for tid in ids:
            b = self.token_bytes(int(tid))
            if b is None:
                if not skip_special:
                    name = self.specials[int(tid) - _N_BYTES
                                         - len(self.merges)] \
                        if 0 <= int(tid) - _N_BYTES - len(self.merges) \
                        < len(self.specials) else f"<|{int(tid)}|>"
                    buf.extend(name.encode("utf-8"))
                continue
            buf.extend(b)
        return buf.decode("utf-8", errors="replace")

    def stream_detokenizer(self) -> "StreamDetokenizer":
        return StreamDetokenizer(self)

    # -- vocab files ---------------------------------------------------------
    def save(self, path: str) -> None:
        payload = {
            "tokens": [m.decode("latin-1") for m in self.merges],
            "specials": self.specials,
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)

    @classmethod
    def load(cls, path: str) -> "ByteTokenizer":
        """Vocab-file loader: JSON ``{"tokens", "specials"}`` (tokens are
        latin-1-escaped byte strings, the ``save`` format) or plain text
        with one merge token per line (``\\xNN`` escapes allowed)."""
        if not os.path.exists(path):
            raise FileNotFoundError(f"vocab file {path} does not exist")
        with open(path) as f:
            text = f.read()
        try:
            payload = json.loads(text)
        except json.JSONDecodeError:
            merges = [line.encode("utf-8").decode("unicode_escape")
                      .encode("latin-1")
                      for line in text.splitlines() if line]
            return cls(merges=merges)
        if not isinstance(payload, dict) or "tokens" not in payload:
            raise ValueError(f"{path}: expected a JSON object with a "
                             "'tokens' list (or a plain token-per-line "
                             "file)")
        return cls(merges=[t.encode("latin-1") for t in payload["tokens"]],
                   specials=payload.get("specials"))


class StreamDetokenizer:
    """Incremental byte→text decoder for live token streams.

    ``push(id)`` returns the text that became decodable with this token
    (often ``""`` mid-multibyte-character); ``flush()`` returns whatever
    is still buffered, replacing a trailing incomplete sequence. Special
    ids are skipped."""

    def __init__(self, tokenizer: ByteTokenizer):
        self._tok = tokenizer
        self._buf = bytearray()

    def push(self, tid: int) -> str:
        b = self._tok.token_bytes(int(tid))
        if b is None:
            return ""
        self._buf.extend(b)
        # longest prefix of complete utf-8 sequences: scan back over at
        # most 3 trailing continuation bytes for an unfinished lead byte
        cut = len(self._buf)
        for back in range(1, min(4, cut) + 1):
            byte = self._buf[cut - back]
            if byte < 0x80:               # ascii — complete
                break
            if byte >= 0xC0:              # lead byte: complete iff its
                need = 2 if byte < 0xE0 else 3 if byte < 0xF0 else 4
                if back < need:           # sequence is still short
                    cut -= back
                break
        if cut == 0:
            return ""
        out = bytes(self._buf[:cut]).decode("utf-8", errors="replace")
        del self._buf[:cut]
        return out

    def flush(self) -> str:
        out = bytes(self._buf).decode("utf-8", errors="replace")
        self._buf.clear()
        return out
