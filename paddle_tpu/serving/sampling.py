"""Token sampling for the serving engine — vectorized, per-slot params.

One fused filter chain covers greedy, temperature, top-k and top-p so it
can ride inside the jitted decode step: every slot in the batch carries
its OWN (temperature, top_k, top_p) triple, which is what continuous
batching needs — requests with different sampling settings share one
compiled program. ``temperature <= 0`` means greedy (argmax of the raw
logits), ``top_k <= 0`` and ``top_p >= 1`` disable those filters.

Per-slot RNG streams (ISSUE 10): :func:`stream_keys` folds each slot's
REQUEST id and per-request draw index into the engine's base key, so a
stream's sampled tokens depend only on (seed, request id, draw index) —
never on which neighbors happen to share the batch, which slot index the
request landed in, or how many scheduler ticks the engine has run.
Eviction/admission of a neighbor therefore cannot perturb a stream, and
a preempted-and-resumed request replays its remaining draws exactly.

Speculative decoding (ISSUE 10): :func:`spec_accept` applies the
standard rejection-sampling rule (Leviathan et al., 2023) to a draft's k
proposals against the target's k+1 verify logits. Both distributions go
through the SAME filter chain, so temperature/top-k/top-p sampling keeps
the target distribution exactly, and greedy reduces to "accept while the
draft token equals the target argmax" — token-identical to the
non-speculative engine by construction.

Constrained decoding (ISSUE 11): every sampling entry point takes an
optional per-row token MASK (B, V) bool — False entries are suppressed
BEFORE temperature/top-k/top-p, so the filter chain renormalizes over
the allowed set and greedy rows argmax the masked logits. The serving
engine feeds masks from per-request token-mask automata
(serving.constrained); ``mask=None`` (and an all-True mask) leave every
path bit-identical to the unmasked code.

Everything here is pure jnp, so the FLAGS_serving_jit=0 reference path
runs the SAME code un-jitted.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_tokens", "sample_tokens_streams", "stream_keys",
           "spec_accept", "MASKED_LOGIT"]

# suppression value for masked-out vocabulary entries: finite (softmax
# over an all-masked row stays NaN-free long enough to be caught
# host-side) but far below any real logit
MASKED_LOGIT = -1e30


def _apply_mask(logits, mask):
    """Suppress disallowed tokens; ``mask`` (B, V) bool or None. An
    all-True mask is the identity (jnp.where copies through), keeping
    unconstrained engines bit-identical."""
    if mask is None:
        return logits
    return jnp.where(mask, logits, jnp.float32(MASKED_LOGIT))


def _filter_logits(logits, temperature, top_k, top_p):
    """Temperature scale → top-k → top-p (nucleus, on the k-filtered
    distribution); logits (B, V) fp32, per-row params. Returns filtered
    logits with suppressed entries at -inf. The usual serving filter
    order — shared by the sampling draw AND the speculative
    accept/residual math so both see the same distribution.

    Pure unconditional math — safe to call eagerly (``lax.cond`` in
    eager mode re-traces and re-compiles per call, a ~0.3s stall each
    time; see :func:`_filter_logits_cond` for the jit-context variant
    that skips the sorts when no row enables the filters)."""
    V = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # top-k with per-row k: keep values >= the k-th largest
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p: keep the smallest prefix of the sorted distribution whose
    # mass reaches top_p (the top token always survives)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive_cum < top_p[:, None]
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(scaled >= cutoff, scaled, -jnp.inf)


def _filter_logits_cond(logits, temperature, top_k, top_p):
    """JIT-CONTEXT filter: the sort-based k/p filters only RUN when some
    row enables them (with every top_k <= 0 and top_p >= 1 they are
    mathematically the identity, and two (B, V) sorts per draw is real
    money on a CPU host). Only call from inside a jitted program —
    eager ``lax.cond`` re-compiles per call."""
    need = jnp.any(top_k > 0) | jnp.any(top_p < 1.0)
    return jax.lax.cond(
        need,
        lambda lg: _filter_logits(lg, temperature, top_k, top_p),
        lambda lg: lg / jnp.maximum(temperature, 1e-6)[:, None],
        logits)


def _finish(logits, scaled, gumbel, temperature):
    """Greedy rows take the raw argmax; sampled rows the Gumbel draw."""
    sampled = jnp.argmax(scaled + gumbel, axis=-1)
    return jnp.where(temperature <= 0.0, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


def sample_tokens(logits, key, temperature, top_k, top_p, mask=None):
    """logits (B, V) fp32 → token ids (B,) int32; ONE key for the batch.

    temperature/top_p: (B,) float32; top_k: (B,) int32; ``mask`` (B, V)
    bool suppresses disallowed tokens ahead of the filter chain
    (constrained decoding). The historical shared-key entry point —
    unconditional math, safe to call eagerly (the reference-decode
    escape hatch and one-off host-side draws); the engine's jitted
    steps use :func:`sample_tokens_streams`, which adds the runtime
    greedy/filter short-circuits."""
    logits = _apply_mask(logits.astype(jnp.float32), mask)
    scaled = _filter_logits(logits, temperature, top_k, top_p)
    gumbel = jax.random.gumbel(key, logits.shape, jnp.float32)
    return _finish(logits, scaled, gumbel, temperature)


def stream_keys(base_key, req_ids, draws):
    """Per-slot sampling keys: fold (request id, per-request draw index)
    into the engine's base key. req_ids/draws (B,) int32 → keys (B,).

    The draw index is the number of tokens the request has sampled so
    far, so a stream is a pure function of (seed, request id) — batch
    composition, slot placement and tick count cannot perturb it."""
    def one(rid, d):
        return jax.random.fold_in(jax.random.fold_in(base_key, rid), d)

    return jax.vmap(one)(req_ids, draws)


def sample_tokens_streams(logits, keys, temperature, top_k, top_p,
                          mask=None):
    """Like :func:`sample_tokens` but each row draws from its OWN key
    (see :func:`stream_keys`); logits (B, V), keys (B,); ``mask``
    (B, V) bool suppresses disallowed tokens first (greedy rows argmax
    the masked logits). All-greedy batches short-circuit to argmax (no
    filters, no RNG). JIT-context only — the short-circuits are
    ``lax.cond``, which re-compiles per call when run eagerly."""
    logits = _apply_mask(logits.astype(jnp.float32), mask)
    V = logits.shape[1]

    def sampled(logits):
        scaled = _filter_logits_cond(logits, temperature, top_k, top_p)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (V,), jnp.float32))(keys)
        return _finish(logits, scaled, gumbel, temperature)

    return jax.lax.cond(
        jnp.any(temperature > 0.0), sampled,
        lambda lg: jnp.argmax(lg, axis=-1).astype(jnp.int32), logits)


# salts separating the independent draws a speculative tick makes from
# one request's stream (draft proposal / accept uniform / residual)
DRAFT_SALT = 1
ACCEPT_SALT = 2
RESIDUAL_SALT = 3


def spec_accept(target_logits, draft_logits, draft_tokens, keys,
                temperature, top_k, top_p):
    """Speculative accept/resample (Leviathan et al., 2023 rule).

    target_logits (B, K+1, V) fp32 — the verify pass over [last_token,
    d_1..d_K]: row j is the target's distribution for the token AFTER
    consuming j proposals. draft_logits (B, K, V) — the distributions the
    draft sampled d_{j+1} from. draft_tokens (B, K). keys (B,) — one
    acceptance stream per slot (fold ACCEPT_SALT/RESIDUAL_SALT inside).

    Returns ``(tokens (B, K+1) int32, n_emit (B,) int32)``: row b emits
    ``tokens[b, :n_emit[b]]`` — the accepted prefix of the draft plus ONE
    token from the target (the rejection-resample at the first miss, or
    the bonus draw when everything passed), so every tick advances every
    row by at least one token. Greedy rows accept while the proposal
    equals the target argmax; sampled rows accept d with probability
    ``min(1, p(d)/q(d))`` and resample from ``normalize(max(0, p - q))``
    — both p and q are the FILTERED distributions, so the emitted stream
    keeps the target distribution exactly."""
    B, K1, V = target_logits.shape
    K = K1 - 1
    target_logits = target_logits.astype(jnp.float32)
    greedy = temperature <= 0.0                                    # (B,)
    tgt_argmax = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)
    acc_greedy = draft_tokens == tgt_argmax[:, :K]

    def emit(m, correction):
        idx = jnp.arange(K1)[None, :]
        d_pad = jnp.concatenate(
            [draft_tokens, jnp.zeros((B, 1), jnp.int32)], axis=1)
        tokens = jnp.where(
            idx < m[:, None], d_pad,
            jnp.where(idx == m[:, None], correction[:, None], 0))
        return tokens.astype(jnp.int32), (m + 1).astype(jnp.int32)

    def greedy_path(_):
        # accept while the proposal IS the target argmax; the correction
        # is the argmax at the first miss (or the bonus row) — no
        # softmax, no filters, no RNG
        m = jnp.sum(jnp.cumprod(acc_greedy.astype(jnp.int32), axis=-1),
                    axis=-1)
        correction = jnp.take_along_axis(tgt_argmax, m[:, None],
                                         axis=-1)[:, 0]
        return emit(m, correction)

    def sampled_path(_):
        dl = draft_logits.astype(jnp.float32)

        def filt(lg):  # (B, N, V) → filtered, per-row params broadcast
            N = lg.shape[1]
            flat = _filter_logits_cond(lg.reshape(B * N, V),
                                       jnp.repeat(temperature, N),
                                       jnp.repeat(top_k, N),
                                       jnp.repeat(top_p, N))
            return flat.reshape(B, N, V)

        p = jax.nn.softmax(filt(target_logits), axis=-1)   # (B, K+1, V)
        q = jax.nn.softmax(filt(dl), axis=-1)              # (B, K, V)

        # acceptance per proposal
        p_d = jnp.take_along_axis(p[:, :K], draft_tokens[..., None],
                                  axis=-1)[..., 0]         # (B, K)
        q_d = jnp.take_along_axis(q, draft_tokens[..., None],
                                  axis=-1)[..., 0]
        u = jax.vmap(lambda k: jax.random.uniform(
            k, (K,), jnp.float32))(jax.vmap(
                lambda k: jax.random.fold_in(k, ACCEPT_SALT))(keys))
        acc_sampled = u * jnp.maximum(q_d, 1e-20) < p_d
        acc = jnp.where(greedy[:, None], acc_greedy, acc_sampled)
        m = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=-1),
                    axis=-1)                               # (B,) in [0, K]

        # resample ONLY at the selected position m: residual
        # max(0, p_m - q_m) after a rejection, plain p_K at the bonus
        # (q padded with 0 makes that the same formula)
        q_pad = jnp.concatenate([q, jnp.zeros_like(p[:, :1])], axis=1)
        p_m = jnp.take_along_axis(p, m[:, None, None],
                                  axis=1)[:, 0]            # (B, V)
        q_m = jnp.take_along_axis(q_pad, m[:, None, None], axis=1)[:, 0]
        res = jnp.maximum(p_m - q_m, 0.0)
        res_ok = jnp.sum(res, axis=-1, keepdims=True) > 1e-9
        res = jnp.where(res_ok, res, p_m)  # p == q exactly → draw from p
        g = jax.vmap(lambda k: jax.random.gumbel(
            k, (V,), jnp.float32))(jax.vmap(
                lambda k: jax.random.fold_in(k, RESIDUAL_SALT))(keys))
        resampled = jnp.argmax(jnp.log(jnp.maximum(res, 1e-30)) + g,
                               axis=-1).astype(jnp.int32)  # (B,)
        tgt_m = jnp.take_along_axis(tgt_argmax, m[:, None], axis=-1)[:, 0]
        correction = jnp.where(greedy, tgt_m, resampled)
        return emit(m, correction)

    return jax.lax.cond(jnp.any(temperature > 0.0), sampled_path,
                        greedy_path, None)
