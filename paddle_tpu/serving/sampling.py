"""Token sampling for the serving engine — vectorized, per-slot params.

One fused function covers greedy, temperature, top-k and top-p so it can
ride inside the jitted decode step: every slot in the batch carries its
OWN (temperature, top_k, top_p) triple, which is what continuous batching
needs — requests with different sampling settings share one compiled
program. ``temperature <= 0`` means greedy (argmax of the raw logits),
``top_k <= 0`` and ``top_p >= 1`` disable those filters.

The function is pure jnp, so the FLAGS_serving_jit=0 reference path runs
the SAME code un-jitted — greedy outputs are identical across the escape
hatch by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_tokens"]


def sample_tokens(logits, key, temperature, top_k, top_p):
    """logits (B, V) fp32 → token ids (B,) int32.

    temperature/top_p: (B,) float32; top_k: (B,) int32. Filter order
    matches the usual serving convention: temperature scale → top-k →
    top-p (nucleus, on the k-filtered distribution) → Gumbel-argmax draw.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    greedy = temperature <= 0.0
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # top-k with per-row k: keep values >= the k-th largest
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p: keep the smallest prefix of the sorted distribution whose
    # mass reaches top_p (the top token always survives)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive_cum < top_p[:, None]
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)

    gumbel = jax.random.gumbel(key, (B, V), jnp.float32)
    sampled = jnp.argmax(scaled + gumbel, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)
