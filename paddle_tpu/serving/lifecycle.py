"""Elastic replica lifecycle for serving (ISSUE 14).

PR 13 made the serving stack fail LOUDLY — router failover, brownout
ladder, chaos harness — but not heal: the
:class:`~paddle_tpu.serving.router.EngineRouter` only removes dead
replicas, so every crash permanently shrinks capacity, and sustained
brownout pressure has no lever except shedding traffic. This module
closes the loop: :class:`ReplicaSupervisor` owns an ENGINE FACTORY
(same seed/params/config as the live replicas — the sameness that makes
every replay exact) and steers the replica set from the router's health
and the shared :class:`~paddle_tpu.serving.overload.OverloadController`.

**Restart/rejoin.** On replica death (scheduler crash, watchdog
restart-budget exhaustion, wedged tick-age), the supervisor spawns a
replacement through a backoff/quarantine ladder::

    attempt 0              immediate
    attempts 1..Q-1        exponential backoff (backoff_s * 2^(a-1),
                           capped at backoff_cap_s)
    attempts Q..max-1      QUARANTINED (quarantine_s holds — a flapping
                           replica stops burning spawn cycles)
    attempt  max_restarts  give up LOUDLY: orphaned streams fail with
                           the original cause, the slot is marked
                           failed, a lifecycle.give_up span records it

A replica that stays alive ``stable_s`` seconds resets its ladder.
Ladders are keyed by (host, replica id) — in a cross-host fleet
(serving/pod.py) a healthy host re-offering a replica id after a host
swap starts from ITS OWN attempt count, not the dead host's, and
:meth:`ReplicaSupervisor.note_host_offer` makes such a slot immediately
due instead of serving out the old host's quarantine hold. The
replacement re-registers under the SAME replica id
(:meth:`EngineRouter.add_replica` — the failover hook is keyed by
(id, engine) so a stale incarnation cannot unroute its successor), its
request-id space is bumped past the dead engine's (new streams never
alias an adopted one's RNG stream), and before it takes live traffic
its radix prefix tree is RE-WARMED: the top-K hottest routed prefixes
from the router's affinity LRU (stashed at death) replay as background
prefill-only requests (``InferenceEngine.warm_prefix`` — a dedicated
request-id space above 2**30), so a rejoined replica's first-token
latency matches a warm one. While warming, the replica is registered
but NOT ready (``/readyz`` and ``healthy_replicas`` exclude it). If the
whole fleet died, the router PARKED the dying streams as orphans — the
replacement adopts them, token-identical, before opening for traffic.

**Autoscaling.** The supervisor polls the shared OverloadController:
``scale_up_after`` consecutive polls at rung >= ``scale_up_rung`` grow
the set toward ``max_replicas`` (spawn → warm → ready, one scale
event); ``scale_down_after`` consecutive polls at rung 0 with aggregate
occupancy below ``scale_down_occupancy`` drain-and-shrink — the victim
stops receiving placements (:meth:`EngineRouter.begin_drain`), open
streams finish within ``drain_timeout_s`` or MIGRATE to survivors via
``evacuate()`` + the adopt_request token replay (token-identical), then
the engine shuts down. The asymmetric counts mirror the brownout
ladder's hysteresis, and ``scale_cooldown_s`` separates consecutive
scale events, so the set never flaps.

Chaos: ``spawn_fail@restart=N[:times=K]`` makes the factory raise on
the Nth spawn attempt (exercising the ladder), and
``replica_flap@restart=N[:times=K]`` crashes each freshly-rejoined
replica at its next busy scheduler tick — both keyed by the
supervisor's OWN spawn/rejoin counters (``FaultRegistry.take_restart``)
so training fault replay stays clean.

Identity discipline: greedy streams are token-identical across restart,
rejoin, scale-up and drain-shrink events (replays ride the
preemption-resume contract; rejoined sampled streams too, since rid +
seed survive). No supervisor attached = the router is bit-identical to
PR 13.

Gauges: ``serving_replicas_target`` (the steered count),
``serving_replica_restarts``, ``serving_scale_events``,
``prefix_warm_tokens``. Spans: ``lifecycle.restart`` (cause, attempt),
``lifecycle.rejoin`` (warm stats), ``lifecycle.quarantine``,
``lifecycle.give_up``, ``lifecycle.scale_up`` / ``lifecycle.scale_down``
— ``tools/trace_report.py lifecycle_report`` turns them into the
restart-cause table, scale-event timeline and warm verdict.

Thread-safety: all supervisor state is guarded by one condition
variable; long operations (factory spawn, warm replay) run OUTSIDE it
on the supervisor thread. The supervisor is a CLIENT of router and
engines — it owns no device state.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..monitor.flight import dump_flight
from ..monitor.stats import (FAULTS_INJECTED, PREFIX_WARM_TOKENS,
                             SERVING_REPLICA_RESTARTS, SERVING_REPLICAS_TARGET,
                             SERVING_SCALE_EVENTS)
from ..monitor.trace import span
from ..resilience import faults as _faults
from .overload import RUNG_HEALTHY, RUNG_SMALL_CHUNKS

__all__ = ["ReplicaSupervisor", "ReplicaFailed"]


class ReplicaFailed(RuntimeError):
    """The supervisor exhausted ``max_restarts`` for a replica slot:
    carried as the error of any stream still parked on it."""


class _Slot:
    """Lifecycle state of one replica id."""

    __slots__ = ("state", "attempts", "next_try_t", "since_t", "old_rid",
                 "cause", "drain_since", "host")

    def __init__(self, host=None):
        self.state = "live"     # live|pending|quarantined|draining|failed
        self.attempts = 0       # respawn attempts since the last stable run
        self.next_try_t = 0.0   # monotonic time of the next spawn attempt
        self.since_t = time.monotonic()   # when the current engine rejoined
        self.old_rid = 0        # dead engine's request-id watermark
        self.cause = None       # last death cause (restart-span arg)
        self.drain_since = None  # monotonic drain start (scale-down)
        self.host = host        # host the current incarnation runs on


class ReplicaSupervisor:
    """Self-healing + autoscaling controller over an EngineRouter.

    ::

        ctl = OverloadController()
        def factory():
            return InferenceEngine(cfg, params, seed=0, paged=True,
                                   prefix_cache=True, overload=ctl)
        router = EngineRouter([factory(), factory()])
        sup = ReplicaSupervisor(router, factory, max_replicas=4)
        ...
        router.shutdown()       # closes the supervisor too

    ``factory`` must build engines identical to the live replicas
    (same seed/params/config) — that is what makes restart, rejoin and
    migration token-exact. The supervisor attaches itself as
    ``router.supervisor`` (arming orphan parking) and starts its
    monitor thread immediately.
    """

    def __init__(self, router, factory: Callable[[], object], *,
                 min_replicas: int = 1, max_replicas: Optional[int] = None,
                 max_restarts: int = 3, backoff_s: float = 0.1,
                 backoff_cap_s: float = 2.0, quarantine_after: int = 2,
                 quarantine_s: float = 2.0, stable_s: float = 5.0,
                 warm_prefixes: int = 4, warm_timeout_s: float = 30.0,
                 scale_up_rung: int = RUNG_SMALL_CHUNKS,
                 scale_up_after: int = 3, scale_down_after: int = 10,
                 scale_down_occupancy: float = 0.25,
                 scale_cooldown_s: float = 1.0,
                 wedge_timeout_s: Optional[float] = None,
                 drain_timeout_s: float = 5.0, poll_s: float = 0.05):
        if router.supervisor is not None:
            raise ValueError("router already has a supervisor")
        if min_replicas < 1:
            raise ValueError(f"min_replicas={min_replicas} must be >= 1")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(f"max_replicas={max_replicas} below "
                             f"min_replicas={min_replicas}")
        if not 0 < quarantine_after <= max_restarts:
            raise ValueError(
                f"quarantine_after={quarantine_after} must sit in "
                f"[1, max_restarts={max_restarts}] — the ladder is "
                "backoff, then quarantine, then give up")
        self.router = router
        self.factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas) if max_replicas is not None \
            else router.n_replicas
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.quarantine_after = int(quarantine_after)
        self.quarantine_s = float(quarantine_s)
        self.stable_s = float(stable_s)
        self.warm_prefixes = int(warm_prefixes)
        self.warm_timeout_s = float(warm_timeout_s)
        self.scale_up_rung = int(scale_up_rung)
        self.scale_up_after = int(scale_up_after)
        self.scale_down_after = int(scale_down_after)
        self.scale_down_occupancy = float(scale_down_occupancy)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.wedge_timeout_s = float(wedge_timeout_s) \
            if wedge_timeout_s is not None \
            else max(1.0, 2.0 * router.tick_age_budget_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.poll_s = float(poll_s)
        self.overload = router.overload     # the shared brownout ladder
        self._cv = threading.Condition()
        self._slots: Dict[int, _Slot] = {
            e.replica_id: _Slot(host=getattr(e, "host", None))
            for e in router.engines}
        # backoff/quarantine ladders keyed by (host, replica id): a
        # healthy host re-offering a replica id after a host swap must
        # not inherit the dead host's attempt count (ISSUE 19)
        self._ladders: Dict[Tuple[Optional[str], int], int] = {}
        self._target = len(self._slots)
        self._spawn_seq = 0     # factory invocations (spawn_fail space)
        self._rejoin_seq = 0    # completed rejoins (replica_flap space)
        self._scale_events = 0
        self._scale_ups = 0
        self._scale_downs = 0   # COMPLETED drain-shrinks (victim gone)
        self._hot = 0           # consecutive polls at/above scale_up_rung
        self._cool = 0          # consecutive idle-rung-0 polls
        self._last_scale_t = time.monotonic() - self.scale_cooldown_s
        self._stop = False
        self._last_error: Optional[BaseException] = None
        SERVING_REPLICAS_TARGET.set(self._target)
        router.supervisor = self
        self._thread = threading.Thread(target=self._run,
                                        name="serving-supervisor",
                                        daemon=True)
        self._thread.start()

    # -- observability -------------------------------------------------------
    def snapshot(self) -> dict:
        """Operator/readyz view of the lifecycle state."""
        with self._cv:
            return {
                "target": self._target,
                "spawns": self._spawn_seq,
                "rejoins": self._rejoin_seq,
                "scale_events": self._scale_events,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "replicas": {str(rid): {"state": st.state,
                                        "attempts": st.attempts,
                                        "host": st.host}
                             for rid, st in sorted(self._slots.items())},
            }

    @property
    def target_replicas(self) -> int:
        return self._target

    def note_host_offer(self, rid: int, host: Optional[str]) -> bool:
        """A healthy host (re-)offers capacity for replica ``rid``.

        Quarantine is keyed by (host, replica): when the offering host
        differs from the one whose deaths built the current ladder, the
        slot switches to the offering host's own attempt count and
        becomes immediately due — a dead host's quarantine hold must not
        hostage a healthy host re-offering the same replica id after a
        host swap (ISSUE 19). Returns True when the offer unblocked the
        slot. No-op for live/draining/failed slots and same-host offers.
        """
        now = time.monotonic()
        with self._cv:
            st = self._slots.get(int(rid))
            if st is None or st.state not in ("pending", "quarantined"):
                return False
            if st.host == host:
                return False
            self._ladders[(st.host, int(rid))] = st.attempts
            st.attempts = self._ladders.get((host, int(rid)), 0)
            st.host = host
            if st.state == "quarantined":
                st.state = "pending"
            st.next_try_t = now      # due on the next scan
            self._cv.notify_all()
        with span("lifecycle.host_offer", cat="serving",
                  args={"replica": int(rid), "host": str(host)}):
            pass
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop the monitor thread (engines/router are the caller's)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout)

    # -- monitor loop --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    break
                self._cv.wait(self.poll_s)
                if self._stop:
                    break
            try:
                self._scan()
                self._drain_progress()
                self._autoscale()
            except BaseException as e:  # noqa: BLE001 — a scan hiccup must
                # not kill the healer; record it and keep supervising
                with self._cv:
                    self._last_error = e

    def _engine(self, rid: int):
        try:
            return self.router.engine_for(rid)
        except KeyError:
            return None

    def _scan(self) -> None:
        """Death/wedge detection + due respawn attempts."""
        now = time.monotonic()
        with self._cv:
            items = list(self._slots.items())
        for rid, st in items:
            if st.state == "live":
                eng = self._engine(rid)
                if eng is None:
                    continue        # removed externally
                if not eng.alive:
                    self._on_death(rid, st, eng, self._cause_of(eng))
                elif eng.busy and eng.tick_age() > self.wedge_timeout_s:
                    self._on_death(rid, st, eng, "wedged")
            elif st.state in ("pending", "quarantined") \
                    and now >= st.next_try_t:
                self._attempt_respawn(rid, st)
        # ladder reset: a replica that survived stable_s earned it
        with self._cv:
            for rid, st in self._slots.items():
                if st.state == "live" and st.attempts \
                        and now - st.since_t > self.stable_s:
                    st.attempts = 0
                    self._ladders.pop((st.host, rid), None)

    @staticmethod
    def _cause_of(eng) -> str:
        err = getattr(eng, "_error", None)
        return type(err).__name__ if err is not None else "dead"

    def _on_death(self, rid: int, st: _Slot, eng, cause: str) -> None:
        """A live replica died or wedged: unregister it and schedule the
        ladder's next spawn attempt (or give up loudly)."""
        if cause == "wedged":
            # arm the wedged scheduler to fail its streams the moment it
            # wakes — adoption/orphan parking handles them from there
            eng.evacuate()
        old_rid = int(getattr(eng, "_rid", 0))
        host = getattr(eng, "host", None)
        self.router.remove_replica(rid)
        now = time.monotonic()
        with self._cv:
            st.old_rid = max(st.old_rid, old_rid)
            st.cause = cause
            # the ladder belongs to (host, replica), not the bare id:
            # park the dying host's attempt count under its own key and
            # resume whatever count THIS host had accrued before
            if st.host != host:
                self._ladders[(st.host, rid)] = st.attempts
                st.attempts = self._ladders.get((host, rid), 0)
                st.host = host
            self._ladders[(host, rid)] = st.attempts
            if st.attempts >= self.max_restarts:
                self._give_up(rid, st)
                return
            if st.attempts == 0:
                delay, state = 0.0, "pending"          # immediate
            elif st.attempts < self.quarantine_after:
                delay = min(self.backoff_cap_s,
                            self.backoff_s * 2 ** (st.attempts - 1))
                state = "pending"                      # exponential backoff
            else:
                delay, state = self.quarantine_s, "quarantined"
            st.state = state
            st.next_try_t = now + delay
        if state == "quarantined":
            with span("lifecycle.quarantine", cat="serving",
                      args={"replica": rid, "attempts": st.attempts,
                            "hold_s": self.quarantine_s, "cause": cause}):
                pass

    def _give_up(self, rid: int, st: _Slot) -> None:
        # cv held by caller: the loud last rung
        st.state = "failed"
        with span("lifecycle.give_up", cat="serving",
                  args={"replica": rid, "attempts": st.attempts,
                        "cause": st.cause}):
            pass
        # give-up is a capacity-down page: dump the flight ring so the
        # on-call human gets the last seconds of fleet history with the
        # alert (no-op when no recorder is armed)
        dump_flight(f"lifecycle_give_up_r{rid}",
                    extra={"replica": rid, "attempts": st.attempts,
                           "cause": str(st.cause)})
        # fleet routers also pull every OTHER host's ring (ISSUE 20) —
        # async, because this thread holds the supervisor cv and the
        # collection does bounded-per-host RPC
        collect = getattr(self.router, "collect_flight_async", None)
        if callable(collect):
            collect(f"give_up_r{rid}")
        self.router.fail_orphans(ReplicaFailed(
            f"replica {rid} gave up after {st.attempts} restart(s) "
            f"(max_restarts={self.max_restarts}; last cause: {st.cause})"))

    def _spawn(self, cause: str, replica: int, attempt: int):
        """One factory invocation under the spawn_fail fault space;
        returns the engine or raises."""
        self._spawn_seq += 1
        SERVING_REPLICA_RESTARTS.add(1)
        with span("lifecycle.restart", cat="serving",
                  args={"replica": replica, "attempt": attempt,
                        "spawn": self._spawn_seq, "cause": cause}):
            if _faults.ENABLED[0]:
                f = _faults.FAULTS.take_restart("spawn_fail",
                                                self._spawn_seq)
                if f is not None:
                    FAULTS_INJECTED.add()
                    raise _faults.InjectedCrash(
                        f"injected spawn failure (attempt "
                        f"{self._spawn_seq})")
            return self.factory()

    def _attempt_respawn(self, rid: int, st: _Slot) -> None:
        attempt = st.attempts
        with self._cv:
            st.attempts += 1
            self._ladders[(st.host, rid)] = st.attempts
        try:
            eng = self._spawn(st.cause or "dead", rid, attempt)
        except BaseException as e:  # noqa: BLE001 — a failed spawn is a
            # ladder rung, not a supervisor crash
            self._on_spawn_failure(rid, st, e)
            return
        # rid-space carry-forward: new submissions continue the dead
        # engine's request-id numbering, so no live stream adopted by a
        # survivor can alias a fresh one's RNG stream — and a rejoined
        # replica's sampled streams match the fault-free numbering
        with eng._cv:
            eng._rid = max(eng._rid, st.old_rid)
        self.router.add_replica(eng, replica_id=rid, warming=True)
        warm_toks, warm_n = self._warm(eng, rid)
        # a full-fleet death parked its streams: the replacement adopts
        # them (token-identical replay) before opening for new traffic
        adopted = 0
        for req, err in self.router.take_orphans():
            try:
                eng.adopt_request(req)
                adopted += 1
            except RuntimeError:
                req._finish("error", err)
        self.router.mark_ready(rid)
        now = time.monotonic()
        with self._cv:
            st.state = "live"
            st.since_t = now
            st.host = getattr(eng, "host", None)
            self._rejoin_seq += 1
            rejoin = self._rejoin_seq
        with span("lifecycle.rejoin", cat="serving",
                  args={"replica": rid, "attempt": attempt,
                        "warm_tokens": warm_toks, "warm_prefixes": warm_n,
                        "adopted": adopted, "rejoin": rejoin}):
            pass
        if _faults.ENABLED[0]:
            f = _faults.FAULTS.take_restart("replica_flap", rejoin)
            if f is not None:
                FAULTS_INJECTED.add()
                eng.fail_at_tick(1)     # crash at its next busy tick

    def _on_spawn_failure(self, rid: int, st: _Slot, err) -> None:
        now = time.monotonic()
        with self._cv:
            st.cause = f"spawn failed: {type(err).__name__}"
            if st.attempts >= self.max_restarts:
                self._give_up(rid, st)
                return
            if st.attempts < self.quarantine_after:
                delay = min(self.backoff_cap_s,
                            self.backoff_s * 2 ** (st.attempts - 1))
                st.state = "pending"
            else:
                delay = self.quarantine_s
                st.state = "quarantined"
            st.next_try_t = now + delay
            attempts, cause = st.attempts, st.cause
        if st.state == "quarantined":
            with span("lifecycle.quarantine", cat="serving",
                      args={"replica": rid, "attempts": attempts,
                            "hold_s": self.quarantine_s, "cause": cause}):
                pass

    # -- prefix re-warm ------------------------------------------------------
    def _warm(self, eng, rid: int):
        """Replay the hottest routed prefixes as prefill-only requests;
        returns (tokens warmed, prefixes warmed)."""
        if getattr(eng, "_prefix", None) is None:
            return 0, 0
        reqs = []
        for p in self.router.hot_prefixes(self.warm_prefixes):
            if p.size < 1 or p.size >= eng.max_len:
                continue
            reqs.append((p, eng.warm_prefix(p)))
        deadline = time.monotonic() + self.warm_timeout_s
        toks = n = 0
        for p, r in reqs:
            try:
                r.result(timeout=max(0.1, deadline - time.monotonic()))
            except (TimeoutError, RuntimeError):
                continue        # warm is best-effort, never a blocker
            toks += int(p.size)
            n += 1
            PREFIX_WARM_TOKENS.add(int(p.size))
            self.router.note_routed_prefix(p, rid)
        return toks, n

    # -- autoscaling ---------------------------------------------------------
    def _counts(self):
        with self._cv:
            live = [r for r, s in self._slots.items() if s.state == "live"]
            coming = [r for r, s in self._slots.items()
                      if s.state in ("pending", "quarantined")]
            draining = [r for r, s in self._slots.items()
                        if s.state == "draining"]
        return live, coming, draining

    def _occupancy_frac(self, live: List[int]) -> float:
        occ = cap = 0
        for rid in live:
            eng = self._engine(rid)
            if eng is None:
                continue
            occ += int(eng.occupancy) + int(eng.queue_depth)
            cap += int(eng.n_slots)
        return occ / cap if cap else 0.0

    def _autoscale(self) -> None:
        if self.overload is None:
            return
        live, coming, draining = self._counts()
        rung = self.overload.rung
        with self._cv:
            if rung >= self.scale_up_rung:
                self._hot += 1
                self._cool = 0
            elif rung == RUNG_HEALTHY and \
                    self._occupancy_frac(live) < self.scale_down_occupancy:
                self._cool += 1
                self._hot = 0
            else:
                # the in-between band mirrors the brownout ladder's:
                # hold the set, reset both streaks — no flapping
                self._hot = 0
                self._cool = 0
            now = time.monotonic()
            cooled = now - self._last_scale_t >= self.scale_cooldown_s
            want_up = (self._hot >= self.scale_up_after and cooled
                       and not coming and not draining
                       and len(live) + len(coming) < self.max_replicas)
            want_down = (self._cool >= self.scale_down_after and cooled
                         and not coming and not draining
                         and len(live) > self.min_replicas)
            if want_up:
                self._hot = 0
            if want_down:
                self._cool = 0
        if want_up:
            self._scale_up(len(live))
        elif want_down:
            self._scale_down(live)

    def _scale_up(self, n_live: int) -> None:
        try:
            eng = self._spawn("scale_up", -1, 0)
        except BaseException:  # noqa: BLE001 — a failed growth spawn is
            return             # retried after the next sustained-hot streak
        rid = self.router.add_replica(eng, warming=True)
        self._warm(eng, rid)
        self.router.mark_ready(rid)
        now = time.monotonic()
        # span BEFORE the counters: a watcher that saw the scale_events
        # gauge move can rely on the trace row already existing
        with span("lifecycle.scale_up", cat="serving",
                  args={"replica": rid, "from": n_live, "to": n_live + 1,
                        "rung": self.overload.rung}):
            pass
        with self._cv:
            self._slots[rid] = _Slot(host=getattr(eng, "host", None))
            self._target = n_live + 1
            self._scale_events += 1
            self._scale_ups += 1
            self._last_scale_t = now
        SERVING_REPLICAS_TARGET.set(self._target)
        SERVING_SCALE_EVENTS.add(1)

    def _scale_down(self, live: List[int]) -> None:
        # victim: the least-loaded live replica (ties -> highest id, so
        # the original replicas are the last to go)
        victim = max(live, key=lambda r: (-self._load(r), r))
        self.router.begin_drain(victim)
        now = time.monotonic()
        with self._cv:
            st = self._slots[victim]
            st.state = "draining"
            st.drain_since = now
            self._target = len(live) - 1
            self._last_scale_t = now
        SERVING_REPLICAS_TARGET.set(self._target)
        with span("lifecycle.scale_down", cat="serving",
                  args={"replica": victim, "from": len(live),
                        "to": len(live) - 1, "phase": "drain"}):
            pass

    def _load(self, rid: int) -> int:
        eng = self._engine(rid)
        if eng is None:
            return 0
        return int(eng.queue_depth) + int(eng.occupancy)

    def _drain_progress(self) -> None:
        """Advance scale-down victims: finished drains shut down and
        leave the set; overdue ones EVACUATE (open streams migrate to
        survivors through adopt_request, token-identically)."""
        _, _, draining = self._counts()
        now = time.monotonic()
        for rid in draining:
            eng = self._engine(rid)
            if eng is None:
                self._finalize_drain(rid, None)
                continue
            if not eng.alive:
                # evacuated (or crashed): streams already failed over
                self._finalize_drain(rid, eng)
            elif eng.queue_depth == 0 and eng.occupancy == 0:
                self._finalize_drain(rid, eng)      # drained naturally
            else:
                with self._cv:
                    since = self._slots[rid].drain_since
                if since is not None and now - since > self.drain_timeout_s:
                    eng.evacuate()      # migrate leftovers to survivors

    def _finalize_drain(self, rid: int, eng) -> None:
        self.router.remove_replica(rid)
        if eng is not None:
            eng.shutdown(drain=False, timeout=30.0)
        with span("lifecycle.scale_down", cat="serving",
                  args={"replica": rid, "phase": "done"}):
            pass
        with self._cv:
            self._slots.pop(rid, None)
            self._scale_events += 1
            self._scale_downs += 1
        SERVING_SCALE_EVENTS.add(1)

    def __repr__(self):
        snap = self.snapshot()
        return (f"ReplicaSupervisor(target={snap['target']}, "
                f"replicas={snap['replicas']})")
