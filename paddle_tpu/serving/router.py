"""Replicated-engine router with failover (ISSUE 13) and a dynamic
replica set (ISSUE 14).

One :class:`~paddle_tpu.serving.engine.InferenceEngine` is one failure
domain: a poisoned batch, a wedged scheduler or an exhausted watchdog
budget takes every open stream with it. :class:`EngineRouter` fronts N
replicas (same config, same params, same ``seed`` — that sameness is
what makes failover exact) and gives the traffic layer one ``submit``
surface with three behaviors a single engine cannot offer:

**Placement** — each request routes to the replica with the longest
cached RADIX PREFIX match for its prompt (a shared system prompt keeps
landing where its blocks already live, so the PR-11 prefix cache keeps
paying across replicas), falling back to least-loaded (queue depth +
slot occupancy) when no replica holds a match. The router tracks prefix
residency in its own block-aligned LRU map, updated as it routes — a
thread-safe mirror of where each prefix was prefilled — rather than
walking the engines' radix trees from outside their scheduler threads
(those structures are scheduler-owned; reading them cross-thread would
be the exact GL003 race the linter exists to catch).

**Health** — a replica is routable while its scheduler thread is alive,
not shut down, not crash-errored (the watchdog's restart-budget
exhaustion lands here), not WARMING (a lifecycle replacement replaying
its prefix re-warm is registered but takes no live traffic until
``mark_ready``), not DRAINING (a scale-down victim finishes or migrates
its open streams but places nothing new), and its TICK-AGE heartbeat is
fresh: an engine with open work whose scheduler has not completed a
loop iteration within ``tick_age_budget_s`` is wedged and stops
receiving NEW work (its open streams are left to its own
watchdog/deadline machinery — a stall is not proof of death, and
double-serving a stream would be worse than waiting).

**Failover** — when a replica's scheduler DIES (crash, injected
``replica_crash``, watchdog budget exhaustion, lifecycle
``evacuate()``), every open request it would have failed with
``error`` is intercepted via the request's failover hook and ADOPTED by
a survivor through the PR-7/12 preemption-resume contract: re-prefill
``prompt + generated[:-1]``, restore the last token, continue. The
request id (= its RNG stream identity) and the shared seed ride along,
so the survivor's continuation is TOKEN-IDENTICAL to the run the dead
replica would have produced — greedy and sampled both. Only requests
the watchdog already marked poisoned (finish_reason ``"watchdog"``)
fail; a replica-level death never silently drops a healthy stream.
``router_failovers`` counts adoptions, ``serving_replicas_healthy``
tracks the routable set, and a ``router.replica_down`` zero-duration
span records each death for ``tools/trace_report.py overload_report``.

**Lifecycle (ISSUE 14)** — the replica set is DYNAMIC under the router
lock: :meth:`add_replica` / :meth:`remove_replica` let a
:class:`~paddle_tpu.serving.lifecycle.ReplicaSupervisor` close the loop
between health and capacity (restart/rejoin, autoscale). A replacement
REUSES the dead replica's id — the failover hook is keyed by (id,
engine identity), so a stale incarnation's late death can never mark
its successor unroutable — and with no survivor left the router PARKS
dying streams as ORPHANS instead of failing them, for the supervisor's
replacement to adopt (token-identical; without a supervisor attached
the PR-13 fail-loudly behavior is pinned). When a prefix-caching
replica dies, its routed-prefix LRU entries move to a bounded stash
that :meth:`hot_prefixes` serves — the re-warm work-list.

The router is a CLIENT of its engines — it owns no device state and no
thread; health is evaluated at submit time and failover runs on the
dying replica's scheduler thread as its last useful act. With one
replica and no faults the router is a pass-through: output is pinned
token-identical to calling the engine directly.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..monitor.stats import ROUTER_FAILOVERS, SERVING_REPLICAS_HEALTHY
from ..monitor.trace import emit_complete, recording

__all__ = ["EngineRouter"]


class EngineRouter:
    """Route ``submit`` calls across replica InferenceEngines.

    ::

        ctl = OverloadController()                    # optional, shared
        engines = [InferenceEngine(cfg, params, seed=0, overload=ctl)
                   for _ in range(2)]
        router = EngineRouter(engines)
        req = router.submit(prompt, max_new_tokens=64)
        req.result()        # survives a replica crash mid-generation

    Replicas must share vocabulary, tokenizer surface and sampling seed
    (identical constructor args is the supported shape). The router
    re-assigns ``replica_id`` 0..N-1 — trace spans and fault specs
    (``replica_crash@step=N:replica=R``) use these ids; lifecycle
    replacements reuse the id they replace.

    ``tick_age_budget_s``: how stale a BUSY replica's scheduler
    heartbeat may grow before the router stops routing new work to it.

    The front end mounts a router exactly like an engine
    (``ServingFrontend(router)``) — tokenizer / config / prefill-chunk /
    overload are proxied from the replicas, and ``/readyz`` degrades to
    "any healthy replica".
    """

    def __init__(self, engines, tick_age_budget_s: float = 5.0,
                 affinity_entries: int = 4096):
        engines = list(engines)
        if not engines:
            raise ValueError("EngineRouter needs at least one engine")
        self.tick_age_budget_s = float(tick_age_budget_s)
        self._lock = threading.Lock()
        # replica id -> engine: the DYNAMIC replica set (ISSUE 14)
        self._replicas: Dict[int, object] = {}
        self._dead: set = set()
        self._warming: set = set()      # registered, re-warming, unroutable
        self._draining: set = set()     # scale-down victims: no placements
        # the attached ReplicaSupervisor (set by its constructor); None =
        # PR-13 behavior pinned: no orphan parking, no lifecycle states
        self.supervisor = None
        # block-aligned prefix -> replica LRU map (see module docstring);
        # affinity only matters when some replica actually caches prefixes
        self._aff_block = None
        self._affinity: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._aff_cap = int(affinity_entries)
        # prefixes routed to now-dead replicas, most recent last — the
        # supervisor's re-warm work-list (bounded like the live map)
        self._dead_prefixes: "collections.OrderedDict[bytes, None]" = \
            collections.OrderedDict()
        # streams a dying replica could not fail over (no survivors):
        # parked for the supervisor's replacement instead of failed
        self._orphans: List[Tuple[object, Optional[BaseException]]] = []
        for i, e in enumerate(engines):
            self.add_replica(e, replica_id=i)

    # -- frontend-facing proxies --------------------------------------------
    @property
    def engines(self) -> List:
        """Current replica engines (registration order); a stable
        snapshot — mutate the set through add/remove_replica."""
        with self._lock:
            return [self._replicas[r] for r in sorted(self._replicas)]

    def engine_for(self, replica: int):
        """The engine currently serving ``replica`` (KeyError if the id
        was removed)."""
        with self._lock:
            return self._replicas[replica]

    @property
    def _any(self):
        with self._lock:
            return next(iter(self._replicas.values()))

    @property
    def tokenizer(self):
        return self._any.tokenizer

    @property
    def cfg(self):
        return self._any.cfg

    @property
    def prefill_chunk(self):
        return self._any.prefill_chunk

    @property
    def overload(self):
        return self._any.overload

    @property
    def queue_depth(self) -> int:
        return sum(e.queue_depth for e in self.engines)

    @property
    def occupancy(self) -> int:
        return sum(e.occupancy for e in self.engines)

    @property
    def n_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    # -- the dynamic replica set (ISSUE 14) ----------------------------------
    def _validate_engine(self, engine) -> None:
        # lock held by caller; compare against any sibling
        for e in self._replicas.values():
            if e.cfg.vocab_size != engine.cfg.vocab_size:
                raise ValueError(
                    "replica configs diverge (vocab "
                    f"{engine.cfg.vocab_size} != {e.cfg.vocab_size}) — "
                    "replicas must serve one model")
            break

    def add_replica(self, engine, replica_id: Optional[int] = None,
                    warming: bool = False) -> int:
        """Register ``engine`` under ``replica_id`` (a reused dead id or
        a fresh one; default = smallest unused). ``warming=True`` keeps
        it out of :meth:`healthy_replicas` until :meth:`mark_ready` —
        registered (visible in ``health()``/readyz) but taking no live
        traffic while its prefix re-warm replays."""
        with self._lock:
            self._validate_engine(engine)
            if replica_id is None:
                replica_id = 0
                while replica_id in self._replicas:
                    replica_id += 1
            replica_id = int(replica_id)
            if replica_id in self._replicas:
                raise ValueError(f"replica id {replica_id} already live")
            engine.replica_id = replica_id
            engine.failover = self._failover_hook(replica_id, engine)
            self._replicas[replica_id] = engine
            self._dead.discard(replica_id)
            self._draining.discard(replica_id)
            if warming:
                self._warming.add(replica_id)
            else:
                self._warming.discard(replica_id)
            if self._aff_block is None \
                    and getattr(engine, "_prefix", None) is not None:
                self._aff_block = int(engine.block_size)
        SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))
        return replica_id

    def mark_ready(self, replica_id: int) -> None:
        """End a replica's warming phase: it joins the routable set."""
        with self._lock:
            self._warming.discard(int(replica_id))
        SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))

    def begin_drain(self, replica_id: int) -> None:
        """Stop placing NEW work on a scale-down victim; its open
        streams keep running (and keep their failover hook, so a later
        ``evacuate()`` migrates them to survivors)."""
        with self._lock:
            self._draining.add(int(replica_id))
        SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))

    def remove_replica(self, replica_id: int):
        """Drop a replica from the set (its failover hook stays armed on
        any streams it still holds). Stashes its routed prefixes for
        re-warm. Returns the removed engine, or None if already gone."""
        replica_id = int(replica_id)
        with self._lock:
            engine = self._replicas.pop(replica_id, None)
            self._dead.discard(replica_id)
            self._warming.discard(replica_id)
            self._draining.discard(replica_id)
            self._purge_affinity(replica_id)
        SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))
        return engine

    # -- orphan streams (no-survivor deaths, supervisor attached) ------------
    def take_orphans(self) -> List[Tuple[object, Optional[BaseException]]]:
        """Claim every parked (request, error) pair — the supervisor
        adopts them onto the replacement replica."""
        with self._lock:
            out, self._orphans = self._orphans, []
        return out

    def fail_orphans(self, err: Optional[BaseException] = None) -> int:
        """Give-up path: fail every parked stream loudly with its
        original (or the supplied) cause. Returns how many."""
        orphans = self.take_orphans()
        for req, cause in orphans:
            req._finish("error", err if err is not None else cause)
        return len(orphans)

    # -- health --------------------------------------------------------------
    def healthy_replicas(self) -> List[int]:
        """Replica ids the router will place NEW work on."""
        with self._lock:
            items = sorted(self._replicas.items())
            unroutable = self._dead | self._warming | self._draining
        out = []
        for i, e in items:
            if i in unroutable or not e.alive:
                continue
            if e.busy and e.tick_age() > self.tick_age_budget_s:
                continue            # wedged: alive but not ticking
            out.append(i)
        return out

    def health(self) -> Dict[int, dict]:
        """Per-replica health view (the /readyz payload)."""
        now_healthy = set(self.healthy_replicas())
        with self._lock:
            items = sorted(self._replicas.items())
            dead, warming = set(self._dead), set(self._warming)
            draining = set(self._draining)
        out = {}
        for i, e in items:
            out[i] = {
                "alive": bool(e.alive), "routable": i in now_healthy,
                "failed_over": i in dead,
                "warming": i in warming,
                "draining": i in draining,
                "tick_age_s": round(e.tick_age(), 3),
                "queue_depth": int(e.queue_depth),
                "occupancy": int(e.occupancy),
                "pool_headroom": round(e.pool_headroom(), 4),
            }
        return out

    def fleet_members(self) -> Dict:
        """Per-replica fleet membership (ISSUE 19): which HOST each
        replica lives on, its fleet role, and how stale that host's
        heartbeat is. In-process engines report ``host None`` / role
        ``"mixed"`` with age 0.0 — their heartbeat is the scheduler tick
        itself, already covered by ``health()``'s tick_age_s. The
        frontend joins this into ``/readyz`` as ``checks.fleet`` so an
        operator can see where a replica physically runs."""
        with self._lock:
            items = sorted(self._replicas.items())
        out = {}
        for i, e in items:
            age = getattr(e, "heartbeat_age", None)
            out[i] = {"host": getattr(e, "host", None),
                      "role": getattr(e, "role", "mixed"),
                      "heartbeat_age_s": round(float(age()), 3)
                      if callable(age) else 0.0}
        return out

    # -- placement -----------------------------------------------------------
    def _load(self, replica: int) -> int:
        e = self.engine_for(replica)
        return int(e.queue_depth) + int(e.occupancy)

    def _affinity_match(self, ids: np.ndarray, healthy) -> Optional[tuple]:
        """Longest block-aligned routed prefix of ``ids`` held by a
        healthy replica -> (replica, matched_tokens)."""
        if self._aff_block is None:
            return None
        B = self._aff_block
        healthy = set(healthy)
        with self._lock:
            for n in range(min(ids.size // B, 64), 0, -1):
                key = ids[:n * B].tobytes()
                rep = self._affinity.get(key)
                if rep is not None and rep in healthy:
                    self._affinity.move_to_end(key)
                    return rep, n * B
        return None

    def _affinity_note(self, ids: np.ndarray, replica: int) -> None:
        if self._aff_block is None \
                or getattr(self.engine_for(replica), "_prefix",
                           None) is None:
            return
        B = self._aff_block
        with self._lock:
            for n in range(1, min(ids.size // B, 64) + 1):
                self._affinity[ids[:n * B].tobytes()] = replica
                self._affinity.move_to_end(ids[:n * B].tobytes())
            while len(self._affinity) > self._aff_cap:
                self._affinity.popitem(last=False)

    def note_routed_prefix(self, ids, replica: int) -> None:
        """Public twin of the internal affinity note: the supervisor
        calls it after re-warming a prefix onto a rejoined replica, so
        placement immediately routes matching prompts there."""
        self._affinity_note(np.asarray(ids, np.int32).reshape(-1),
                            int(replica))

    def _purge_affinity(self, replica: int) -> None:
        # lock held by caller; the dead replica's routed prefixes move
        # to the re-warm stash (most recent last) instead of vanishing
        stale = [k for k, r in self._affinity.items() if r == replica]
        for k in stale:
            del self._affinity[k]
            self._dead_prefixes[k] = None
            self._dead_prefixes.move_to_end(k)
        while len(self._dead_prefixes) > self._aff_cap:
            self._dead_prefixes.popitem(last=False)

    def hot_prefixes(self, k: int = 4) -> List[np.ndarray]:
        """The top-``k`` hottest routed prefixes (most recent first,
        MAXIMAL only — a prefix of a hotter entry is redundant), drawn
        from the dead-replica stash first, then the live affinity map.
        This is the supervisor's re-warm work-list; empty when no
        replica caches prefixes."""
        with self._lock:
            keys = list(reversed(self._dead_prefixes)) \
                + list(reversed(self._affinity))
        out: List[bytes] = []
        for key in keys:
            if any(kept.startswith(key) for kept in out):
                continue        # a hotter, longer entry already covers it
            out = [kept for kept in out if not key.startswith(kept)]
            out.append(key)     # ...and this one extends any it covers
            if len(out) >= int(k):
                break
        return [np.frombuffer(key, np.int32).copy() for key in out[:int(k)]]

    def place(self, prompt) -> Optional[int]:
        """Replica for this prompt: longest cached prefix match first,
        least-loaded otherwise. None = no healthy replica."""
        healthy = self.healthy_replicas()
        if not healthy:
            return None
        ids = np.asarray(prompt, np.int32).reshape(-1)
        hit = self._affinity_match(ids, healthy)
        if hit is not None:
            return hit[0]
        return min(healthy, key=self._load)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt=None, text: Optional[str] = None, **kw):
        """Route and submit; returns the engine's GenerationRequest.

        Accepts the full ``InferenceEngine.submit`` surface. ``text`` is
        encoded HERE (one tokenizer, shared by contract) so placement
        sees token ids."""
        if text is not None:
            if prompt is not None:
                raise ValueError("pass prompt OR text, not both")
            if self.tokenizer is None:
                raise ValueError("submit(text=...) needs engines built "
                                 "with a tokenizer")
            prompt = self.tokenizer.encode(text)
            if kw.get("eos_id") is None:
                kw["eos_id"] = self.tokenizer.eos_id
        if prompt is None:
            raise ValueError("provide a prompt (token ids) or text")
        ids = np.asarray(prompt, np.int32).reshape(-1)
        replica = self.place(ids)
        if replica is None:
            raise RuntimeError("EngineRouter: no healthy replica "
                               f"(of {self.n_replicas})")
        req = self.engine_for(replica).submit(prompt=ids, **kw)
        req._replica = replica          # where it lives (failover moves it)
        self._affinity_note(ids, replica)
        return req

    def generate(self, prompt=None, **kw):
        """Blocking convenience wrapper: submit + result."""
        return self.submit(prompt, **kw).result()

    # -- failover ------------------------------------------------------------
    def _failover_hook(self, replica: int, engine):
        def hook(req, err) -> bool:
            return self._replica_failed(replica, engine, req, err)
        return hook

    def _replica_failed(self, replica: int, engine, req, err) -> bool:
        """Runs on the DYING replica's scheduler thread, once per open
        request it is failing. True = the request was adopted by a
        survivor (or parked for the supervisor's replacement) — the
        caller must not finish it."""
        with self._lock:
            # a replacement may have REUSED this id: only the current
            # incarnation's death marks the id dead, a stale engine's
            # late failure must never unroute its successor
            current = self._replicas.get(replica) is engine
            first = current and replica not in self._dead
            if first:
                self._dead.add(replica)
                self._purge_affinity(replica)
        if first:
            SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))
            if recording():
                emit_complete(
                    "router.replica_down", time.perf_counter(), 0.0,
                    cat="serving",
                    args={"replica": replica,
                          "error": f"{type(err).__name__}: {err}"
                          if err is not None else None})
        survivors = self.healthy_replicas()
        target = min(survivors, key=self._load) if survivors else None
        if target is not None:
            try:
                self.engine_for(target).adopt_request(req)
            except (RuntimeError, KeyError):
                target = None   # survivor died/vanished in the window
            else:
                req._replica = target
                ROUTER_FAILOVERS.add(1)
                return True
        if self.supervisor is not None:
            # nobody left to adopt it NOW — park for the supervisor's
            # replacement (the restart/rejoin path) instead of failing
            with self._lock:
                self._orphans.append((req, err))
            return True
        return False            # no supervisor: the error goes through

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        if self.supervisor is not None:
            self.supervisor.close()
        for e in self.engines:
            e.shutdown(drain=drain, timeout=timeout)
        self.fail_orphans()

    def __repr__(self):
        return (f"EngineRouter(replicas={self.n_replicas}, "
                f"healthy={self.healthy_replicas()})")
