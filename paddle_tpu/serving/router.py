"""Replicated-engine router with failover (ISSUE 13).

One :class:`~paddle_tpu.serving.engine.InferenceEngine` is one failure
domain: a poisoned batch, a wedged scheduler or an exhausted watchdog
budget takes every open stream with it. :class:`EngineRouter` fronts N
replicas (same config, same params, same ``seed`` — that sameness is
what makes failover exact) and gives the traffic layer one ``submit``
surface with three behaviors a single engine cannot offer:

**Placement** — each request routes to the replica with the longest
cached RADIX PREFIX match for its prompt (a shared system prompt keeps
landing where its blocks already live, so the PR-11 prefix cache keeps
paying across replicas), falling back to least-loaded (queue depth +
slot occupancy) when no replica holds a match. The router tracks prefix
residency in its own block-aligned LRU map, updated as it routes — a
thread-safe mirror of where each prefix was prefilled — rather than
walking the engines' radix trees from outside their scheduler threads
(those structures are scheduler-owned; reading them cross-thread would
be the exact GL003 race the linter exists to catch).

**Health** — a replica is routable while its scheduler thread is alive,
not shut down, not crash-errored (the watchdog's restart-budget
exhaustion lands here), and its TICK-AGE heartbeat is fresh: an engine
with open work whose scheduler has not completed a loop iteration
within ``tick_age_budget_s`` is wedged and stops receiving NEW work
(its open streams are left to its own watchdog/deadline machinery — a
stall is not proof of death, and double-serving a stream would be
worse than waiting).

**Failover** — when a replica's scheduler DIES (crash, injected
``replica_crash``, watchdog budget exhaustion), every open request it
would have failed with ``error`` is intercepted via the request's
failover hook and ADOPTED by a survivor through the PR-7/12
preemption-resume contract: re-prefill ``prompt + generated[:-1]``,
restore the last token, continue. The request id (= its RNG stream
identity) and the shared seed ride along, so the survivor's
continuation is TOKEN-IDENTICAL to the run the dead replica would have
produced — greedy and sampled both. Only requests the watchdog already
marked poisoned (finish_reason ``"watchdog"``) fail; a replica-level
death never silently drops a healthy stream. ``router_failovers``
counts adoptions, ``serving_replicas_healthy`` tracks the routable set,
and a ``router.replica_down`` zero-duration span records each death for
``tools/trace_report.py overload_report``.

The router is a CLIENT of its engines — it owns no device state and no
thread; health is evaluated at submit time and failover runs on the
dying replica's scheduler thread as its last useful act. With one
replica and no faults the router is a pass-through: output is pinned
token-identical to calling the engine directly.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..monitor.stats import ROUTER_FAILOVERS, SERVING_REPLICAS_HEALTHY
from ..monitor.trace import TRACING, get_writer

__all__ = ["EngineRouter"]


class EngineRouter:
    """Route ``submit`` calls across replica InferenceEngines.

    ::

        ctl = OverloadController()                    # optional, shared
        engines = [InferenceEngine(cfg, params, seed=0, overload=ctl)
                   for _ in range(2)]
        router = EngineRouter(engines)
        req = router.submit(prompt, max_new_tokens=64)
        req.result()        # survives a replica crash mid-generation

    Replicas must share vocabulary, tokenizer surface and sampling seed
    (identical constructor args is the supported shape). The router
    re-assigns ``replica_id`` 0..N-1 — trace spans and fault specs
    (``replica_crash@step=N:replica=R``) use these ids.

    ``tick_age_budget_s``: how stale a BUSY replica's scheduler
    heartbeat may grow before the router stops routing new work to it.

    The front end mounts a router exactly like an engine
    (``ServingFrontend(router)``) — tokenizer / config / prefill-chunk /
    overload are proxied from the replicas, and ``/readyz`` degrades to
    "any healthy replica".
    """

    def __init__(self, engines, tick_age_budget_s: float = 5.0,
                 affinity_entries: int = 4096):
        engines = list(engines)
        if not engines:
            raise ValueError("EngineRouter needs at least one engine")
        v0 = engines[0].cfg.vocab_size
        for e in engines[1:]:
            if e.cfg.vocab_size != v0:
                raise ValueError(
                    "replica configs diverge (vocab "
                    f"{e.cfg.vocab_size} != {v0}) — replicas must serve "
                    "one model")
        self.engines: List = engines
        self.tick_age_budget_s = float(tick_age_budget_s)
        self._lock = threading.Lock()
        self._dead: set = set()
        # block-aligned prefix -> replica LRU map (see module docstring);
        # affinity only matters when some replica actually caches prefixes
        self._aff_block = None
        for e in engines:
            if getattr(e, "_prefix", None) is not None:
                self._aff_block = int(e.block_size)
                break
        self._affinity: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._aff_cap = int(affinity_entries)
        for i, e in enumerate(engines):
            e.replica_id = i
            e.failover = self._failover_hook(i)
        SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))

    # -- frontend-facing proxies --------------------------------------------
    @property
    def tokenizer(self):
        return self.engines[0].tokenizer

    @property
    def cfg(self):
        return self.engines[0].cfg

    @property
    def prefill_chunk(self):
        return self.engines[0].prefill_chunk

    @property
    def overload(self):
        return self.engines[0].overload

    @property
    def queue_depth(self) -> int:
        return sum(e.queue_depth for e in self.engines)

    @property
    def occupancy(self) -> int:
        return sum(e.occupancy for e in self.engines)

    # -- health --------------------------------------------------------------
    def healthy_replicas(self) -> List[int]:
        """Replica ids the router will place NEW work on."""
        out = []
        for i, e in enumerate(self.engines):
            if i in self._dead or not e.alive:
                continue
            if e.busy and e.tick_age() > self.tick_age_budget_s:
                continue            # wedged: alive but not ticking
            out.append(i)
        return out

    def health(self) -> Dict[int, dict]:
        """Per-replica health view (the /readyz payload)."""
        now_healthy = set(self.healthy_replicas())
        out = {}
        for i, e in enumerate(self.engines):
            out[i] = {
                "alive": bool(e.alive), "routable": i in now_healthy,
                "failed_over": i in self._dead,
                "tick_age_s": round(e.tick_age(), 3),
                "queue_depth": int(e.queue_depth),
                "occupancy": int(e.occupancy),
                "pool_headroom": round(e.pool_headroom(), 4),
            }
        return out

    # -- placement -----------------------------------------------------------
    def _load(self, replica: int) -> int:
        e = self.engines[replica]
        return int(e.queue_depth) + int(e.occupancy)

    def _affinity_match(self, ids: np.ndarray, healthy) -> Optional[tuple]:
        """Longest block-aligned routed prefix of ``ids`` held by a
        healthy replica -> (replica, matched_tokens)."""
        if self._aff_block is None:
            return None
        B = self._aff_block
        healthy = set(healthy)
        with self._lock:
            for n in range(min(ids.size // B, 64), 0, -1):
                key = ids[:n * B].tobytes()
                rep = self._affinity.get(key)
                if rep is not None and rep in healthy:
                    self._affinity.move_to_end(key)
                    return rep, n * B
        return None

    def _affinity_note(self, ids: np.ndarray, replica: int) -> None:
        if self._aff_block is None \
                or getattr(self.engines[replica], "_prefix", None) is None:
            return
        B = self._aff_block
        with self._lock:
            for n in range(1, min(ids.size // B, 64) + 1):
                self._affinity[ids[:n * B].tobytes()] = replica
                self._affinity.move_to_end(ids[:n * B].tobytes())
            while len(self._affinity) > self._aff_cap:
                self._affinity.popitem(last=False)

    def _purge_affinity(self, replica: int) -> None:
        # lock held by caller
        stale = [k for k, r in self._affinity.items() if r == replica]
        for k in stale:
            del self._affinity[k]

    def place(self, prompt) -> Optional[int]:
        """Replica for this prompt: longest cached prefix match first,
        least-loaded otherwise. None = no healthy replica."""
        healthy = self.healthy_replicas()
        if not healthy:
            return None
        ids = np.asarray(prompt, np.int32).reshape(-1)
        hit = self._affinity_match(ids, healthy)
        if hit is not None:
            return hit[0]
        return min(healthy, key=self._load)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt=None, text: Optional[str] = None, **kw):
        """Route and submit; returns the engine's GenerationRequest.

        Accepts the full ``InferenceEngine.submit`` surface. ``text`` is
        encoded HERE (one tokenizer, shared by contract) so placement
        sees token ids."""
        if text is not None:
            if prompt is not None:
                raise ValueError("pass prompt OR text, not both")
            if self.tokenizer is None:
                raise ValueError("submit(text=...) needs engines built "
                                 "with a tokenizer")
            prompt = self.tokenizer.encode(text)
            if kw.get("eos_id") is None:
                kw["eos_id"] = self.tokenizer.eos_id
        if prompt is None:
            raise ValueError("provide a prompt (token ids) or text")
        ids = np.asarray(prompt, np.int32).reshape(-1)
        replica = self.place(ids)
        if replica is None:
            raise RuntimeError("EngineRouter: no healthy replica "
                               f"(of {len(self.engines)})")
        req = self.engines[replica].submit(prompt=ids, **kw)
        req._replica = replica          # where it lives (failover moves it)
        self._affinity_note(ids, replica)
        return req

    def generate(self, prompt=None, **kw):
        """Blocking convenience wrapper: submit + result."""
        return self.submit(prompt, **kw).result()

    # -- failover ------------------------------------------------------------
    def _failover_hook(self, replica: int):
        def hook(req, err) -> bool:
            return self._replica_failed(replica, req, err)
        return hook

    def _replica_failed(self, replica: int, req, err) -> bool:
        """Runs on the DYING replica's scheduler thread, once per open
        request it is failing. True = the request was adopted by a
        survivor (the caller must not finish it)."""
        with self._lock:
            first = replica not in self._dead
            if first:
                self._dead.add(replica)
                self._purge_affinity(replica)
        if first:
            SERVING_REPLICAS_HEALTHY.set(len(self.healthy_replicas()))
            if TRACING[0]:
                get_writer().add_complete(
                    "router.replica_down", time.perf_counter(), 0.0,
                    cat="serving",
                    args={"replica": replica,
                          "error": f"{type(err).__name__}: {err}"
                          if err is not None else None})
        survivors = self.healthy_replicas()
        if not survivors:
            return False        # nobody left: the error goes through
        target = min(survivors, key=self._load)
        try:
            self.engines[target].adopt_request(req)
        except RuntimeError:
            return False        # survivor died in the window: fail loudly
        req._replica = target
        ROUTER_FAILOVERS.add(1)
        return True

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        for e in self.engines:
            e.shutdown(drain=drain, timeout=timeout)

    def __repr__(self):
        return (f"EngineRouter(replicas={len(self.engines)}, "
                f"healthy={self.healthy_replicas()})")
