"""paddle_tpu.serving — continuous-batching inference engine (ISSUE 4/7/10).

The generation-side counterpart of ``paddle_tpu.inference``: where the
Predictor serves one compiled program per call (the reference's
AnalysisPredictor shape), this package serves AUTOREGRESSIVE workloads —
many concurrent requests sharing one jitted KV-cache decode step,
Orca-style continuous batching instead of request-at-a-time.

Layers:

- :mod:`kv_cache` — two cache shapes. :class:`KVCache`: fixed-slot
  donated device buffers ``(slots, layers, heads, max_len, head_dim)``.
  :class:`PagedKVCache` (``FLAGS_paged_kv=1``): a shared block pool
  ``(n_blocks, layers, heads, block_size, head_dim)`` + per-slot block
  tables and host-side free lists — slot memory proportional to LIVE
  tokens, admission gated on free blocks instead of a fixed ``max_len``,
  with ``kv_blocks_free`` / ``kv_blocks_used`` / ``kv_fragmentation``
  gauges and loud ``AssertionError`` on free-list double-frees. With
  ``shards=D`` (multi-chip) the pool partitions into per-shard block
  ranges with per-shard free lists and garbage sinks, so every lookup
  and scatter stays local to the chip holding that slot's lane;
- :func:`paddle_tpu.models.gpt_prefill` /
  :func:`paddle_tpu.models.gpt_decode_step` — the cache-aware forward
  variants (they live with the model); paged mode adds
  :func:`~paddle_tpu.models.gpt_prefill_chunk` and
  :func:`~paddle_tpu.models.gpt_decode_step_paged` (Pallas
  paged-attention kernel on TPU); speculative decoding adds the
  multi-token verify passes :func:`~paddle_tpu.models.gpt_verify_step`
  / ``gpt_verify_step_paged`` — k+1 positions scored in one program;
- :mod:`sampling` — fused greedy/temperature/top-k/top-p with per-slot
  parameters, per-REQUEST RNG streams (``stream_keys`` folds request id
  + draw index, so a stream's sampled tokens never depend on batch
  neighbors) and the speculative accept/resample rule
  (:func:`~paddle_tpu.serving.sampling.spec_accept`);
- :mod:`tokenizer` — the byte-level text front end:
  :class:`ByteTokenizer` (byte floor + optional merge vocab file) and
  :class:`StreamDetokenizer` for utf-8-safe live text streaming; give
  the engine one and ``submit(text=...)`` / ``stream_text()`` work;
- :mod:`engine` — the scheduler: bounded queue with backpressure,
  prefill-and-insert admission (paged: CHUNKED prefill interleaved with
  decode; pool-exhaustion preemption requeues the youngest slot), one
  batched decode step per tick, eviction without draining,
  deadlines/cancellation, graceful shutdown, and the serving_* gauges +
  trace spans. ``draft=(cfg, params)`` switches the tick to
  speculative decoding (draft proposes ``spec_k``, target verifies k+1
  in one pass, greedy token-identical to ``draft=None``);
  ``mesh=``/``FLAGS_serving_mesh=D`` shards slots over "data" and
  weights over "model" so the tick runs over a whole TPU slice.

Escape hatches: ``paddle.set_flags({"FLAGS_serving_jit": 0})`` swaps the
jitted cache path for an un-jitted full-recompute reference decode
(speculation pauses — the reference path decodes one token at a time);
``FLAGS_paged_kv=0`` (default) keeps the fixed-slot cache;
``FLAGS_serving_mesh=0`` + ``draft=None`` (defaults) pin the single-chip
non-speculative engine.
"""
from .engine import GenerationRequest, InferenceEngine, QueueFull
from .kv_cache import KVCache, PagedKVCache, cache_insert
from .sampling import sample_tokens, sample_tokens_streams, spec_accept, \
    stream_keys
from .tokenizer import ByteTokenizer, StreamDetokenizer

__all__ = [
    "InferenceEngine", "GenerationRequest", "QueueFull",
    "KVCache", "PagedKVCache", "cache_insert",
    "sample_tokens", "sample_tokens_streams", "stream_keys", "spec_accept",
    "ByteTokenizer", "StreamDetokenizer",
]
