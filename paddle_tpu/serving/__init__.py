"""paddle_tpu.serving — continuous-batching inference engine and its
production traffic layer (ISSUE 4/7/10/11).

The generation-side counterpart of ``paddle_tpu.inference``: where the
Predictor serves one compiled program per call (the reference's
AnalysisPredictor shape), this package serves AUTOREGRESSIVE workloads —
many concurrent requests sharing one jitted KV-cache decode step,
Orca-style continuous batching instead of request-at-a-time — and, as
of ISSUE 11, speaks HTTP to real multi-tenant traffic.

Layers, bottom up:

- :mod:`kv_cache` — two cache shapes. :class:`KVCache`: fixed-slot
  donated device buffers ``(slots, layers, heads, max_len, head_dim)``.
  :class:`PagedKVCache` (``FLAGS_paged_kv=1``): a shared block pool
  ``(n_blocks, layers, heads, block_size, head_dim)`` + per-slot block
  tables, host-side free lists and PER-BLOCK REFCOUNTS — slot memory
  proportional to LIVE tokens, admission gated on free blocks,
  ``free_slot`` decrements instead of freeing so blocks can be SHARED
  across slots (``splice``/``ref_block``/``replace_block`` are the
  prefix cache's contract), with ``kv_blocks_free`` / ``kv_blocks_used``
  / ``kv_fragmentation`` gauges and loud ``AssertionError`` on
  refcount/free-list corruption. ``shards=D`` (multi-chip) partitions
  the pool into per-shard block ranges;
- :mod:`prefix_cache` — :class:`~prefix_cache.RadixPrefixCache`
  (``FLAGS_prefix_cache=1``): a host-side radix tree keyed by token-id
  block chunks over that pool. Admission walks it, bumps refcounts on
  matched blocks and splices them into the new slot's table, so a
  shared system prompt prefills ONCE and fans out; only the uncached
  tail runs (``models.gpt_prefill_prefix`` continues from an unaligned
  cached length), a partially-used last block is copy-on-write
  duplicated first, and eviction is LRU-by-leaf over refcount-0 nodes —
  composing with, not replacing, pool-exhaustion preemption. Greedy
  output is pinned token-identical to the cache-cold engine;
- :func:`paddle_tpu.models.gpt_prefill` / ``gpt_decode_step`` /
  ``gpt_prefill_chunk`` / ``gpt_prefill_prefix`` /
  ``gpt_decode_step_paged`` / ``gpt_verify_step`` (+``_paged``) — the
  cache-aware forward variants (they live with the model);
- :mod:`sampling` — fused greedy/temperature/top-k/top-p with per-slot
  parameters, per-REQUEST RNG streams, the speculative accept/resample
  rule, and per-row token MASKS (``mask=``) so constrained rows ride
  the same compiled program;
- :mod:`constrained` — structured decoding: JSON-schema / regex →
  byte-level DFA → per-state vocabulary masks
  (:func:`~constrained.compile_constraint`,
  :class:`~constrained.TokenConstraint`); pass the result to
  ``submit(constraint=...)`` and the stream ends with
  ``finish_reason="stop"`` when the match completes;
- :mod:`tokenizer` — the byte-level text front end:
  :class:`ByteTokenizer` (byte floor + optional merge vocab file) and
  :class:`StreamDetokenizer` for utf-8-safe live text streaming;
- :mod:`engine` — the scheduler: bounded queue with backpressure,
  prefill-and-insert admission (paged: CHUNKED prefill interleaved with
  decode; prefix-cache splicing; LRU tree reclaim, then youngest-first
  preemption), one batched decode step per tick, speculative decoding
  (``draft=``), multi-chip decode (``mesh=``/``FLAGS_serving_mesh``),
  eviction without draining, deadlines/cancellation, graceful shutdown,
  and the serving_*/prefix_*/constrained_* gauges + trace spans;
- :mod:`overload` — the brownout degradation ladder (ISSUE 13):
  :class:`~overload.OverloadController` EWMAs queue wait and decode
  tick latency against budgets and, with hysteresis, steps healthy →
  no_spec → small_chunks → capped_tokens → shed_bronze → shed_silver;
  the engine consults it for speculation/chunking, the front end for
  per-lane token caps and 503 sheds. No controller attached = pinned
  bit-identical serving;
- :mod:`router` — :class:`~router.EngineRouter` fronts N replica
  engines: least-loaded placement with radix-prefix affinity, health
  from scheduler liveness + tick-age heartbeat, and on replica death
  the open healthy streams are ADOPTED by survivors through the
  preemption-resume contract (token-identical continuations; only
  watchdog-poisoned requests fail). The replica set is DYNAMIC
  (``add_replica`` / ``remove_replica`` under the router lock, warming
  and draining states, orphan parking when the whole fleet dies). One
  replica, no faults = a pass-through pinned token-identical to the
  bare engine;
- :mod:`lifecycle` — :class:`~lifecycle.ReplicaSupervisor` (ISSUE 14)
  closes the health loop: replica death/wedge → respawn through an
  immediate → exponential-backoff → quarantine → give-up-loudly
  ladder, radix prefix RE-WARM from the router's hottest routed
  prefixes before the replacement takes traffic, and brownout-driven
  autoscaling (sustained rung >= ``scale_up_rung`` grows toward
  ``max_replicas``; sustained rung 0 + low occupancy drains-and-
  shrinks, migrating open streams to survivors token-identically).
  No supervisor = bit-identical to the PR-13 router;
- :mod:`rpc` — the stdlib cross-host transport (ISSUE 19): one
  length-prefixed JSON-header + binary-blob frame over TCP
  (:class:`~rpc.RpcServer` / :class:`~rpc.RpcClient` with a per-client
  socket pool so parked long-polls never delay health probes), a
  zero-copy numpy array codec (bfloat16/fp8 via ml_dtypes names), and
  the two-level error contract — :class:`~rpc.RpcError` (transport:
  dead peer, torn frame, timeout — the failover signal) vs
  :class:`~rpc.RpcRemoteError` (the remote handler raised; ``.etype``
  carries the remote type so ``QueueFull`` maps back);
- :mod:`pod` — the cross-HOST fleet (ISSUE 19): hosts run a
  :class:`~pod.HostAgent` (engines + RPC server + registry heartbeat
  over the elastic :class:`FileKVStore`'s checksummed binary records);
  clients :func:`~pod.connect_fleet` into a :class:`~pod.FleetRouter`
  whose :class:`~pod.RemoteReplica` proxies expose the SAME
  submit/stream/adopt/health surface as an in-process engine — router
  affinity, token-replay failover, the supervisor ladder and the
  frontend all compose unchanged across machines. Role-split replicas
  disaggregate serving: prefill-role hosts run chunked prefill only
  and stream finished KV blocks to decode-role hosts, which splice
  them through the refcounted block table (token-identical to
  monolithic, greedy AND sampled); :class:`~pod.FleetScheduler`
  assigns roles, sizes pools per phase and pre-warms decode replicas
  from :class:`~pod.ArrivalRateForecaster` arrival-rate windows ahead
  of the brownout ladder. Host loss = heartbeat staleness → open
  streams re-route through the PR-13 failover contract
  (``tools/trace_report.py fleet_report`` turns the fleet spans into
  per-host utilization and KV-transfer verdicts);
- :mod:`frontend` — the network surface (``python -m
  paddle_tpu.serving.frontend``): a stdlib-asyncio HTTP server with
  OpenAI-style ``/v1/completions`` and ``/v1/chat/completions`` (SSE
  streaming), ``/v1/models``, ``/metrics`` (Prometheus text exposition:
  HELP/TYPE for every gauge + the source-recorded latency histograms as
  ``_bucket``/``_sum``/``_count`` series, ISSUE 15), and
  ``/healthz`` / ``/readyz`` probes; per-tenant API-key auth with
  token-bucket admission and SLO lanes drained by weighted fair
  queuing over prefill chunks. The status contract: **429** = the
  tenant broke its own rate/stream budget; **503 + Retry-After** =
  the server shed the work (engine queue saturated, ``deadline_s``
  expired before generation started, brownout shed rung). Deadlines
  propagate end to end (HTTP admission → WFQ lane → engine admission →
  response waits), an SSE client that disconnects has its engine
  request cancelled (slot/blocks/prefix refs released), and
  ``response_format`` compiles to a :mod:`constrained` automaton.
  ``tools/trace_report.py frontend_report`` / ``overload_report`` turn
  its spans into per-tenant SLO and brownout/replica verdicts.

Escape hatches: ``paddle.set_flags({"FLAGS_serving_jit": 0})`` swaps the
jitted cache path for an un-jitted full-recompute reference decode;
``FLAGS_paged_kv=0`` (default) keeps the fixed-slot cache;
``FLAGS_prefix_cache=0`` (default) keeps every prefill cache-cold;
``FLAGS_serving_mesh=0`` + ``draft=None`` (defaults) pin the
single-chip non-speculative engine; ``overload=None`` + no router
(defaults) pin the PR-11 front end bit-identical.
"""
from .constrained import (ConstraintCursor, TokenConstraint,
                          compile_constraint, compile_regex,
                          schema_to_regex)
from .engine import (GenerationRequest, InferenceEngine, QueueFull,
                     ReplicaEvacuated, WatchdogTripped)
from .kv_cache import KVCache, PagedKVCache, cache_insert
from .lifecycle import ReplicaFailed, ReplicaSupervisor
from .overload import RUNG_NAMES, OverloadController
from .pod import (ArrivalRateForecaster, FleetRegistry, FleetRouter,
                  FleetScheduler, HostAgent, RemoteReplica,
                  RemoteReplicaError, connect_fleet)
from .prefix_cache import RadixPrefixCache
from .router import EngineRouter
from .rpc import RpcClient, RpcError, RpcRemoteError, RpcServer
from .sampling import sample_tokens, sample_tokens_streams, spec_accept, \
    stream_keys
from .tokenizer import ByteTokenizer, StreamDetokenizer

__all__ = [
    "InferenceEngine", "GenerationRequest", "QueueFull",
    "WatchdogTripped", "ReplicaEvacuated",
    "KVCache", "PagedKVCache", "cache_insert", "RadixPrefixCache",
    "OverloadController", "RUNG_NAMES", "EngineRouter",
    "ReplicaSupervisor", "ReplicaFailed",
    "sample_tokens", "sample_tokens_streams", "stream_keys", "spec_accept",
    "ByteTokenizer", "StreamDetokenizer",
    "TokenConstraint", "ConstraintCursor", "compile_constraint",
    "compile_regex", "schema_to_regex",
    "HostAgent", "RemoteReplica", "RemoteReplicaError", "FleetRegistry",
    "FleetRouter",
    "FleetScheduler", "ArrivalRateForecaster", "connect_fleet",
    "RpcServer", "RpcClient", "RpcError", "RpcRemoteError",
]
