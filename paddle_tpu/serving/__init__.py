"""paddle_tpu.serving — continuous-batching inference engine (ISSUE 4/7).

The generation-side counterpart of ``paddle_tpu.inference``: where the
Predictor serves one compiled program per call (the reference's
AnalysisPredictor shape), this package serves AUTOREGRESSIVE workloads —
many concurrent requests sharing one jitted KV-cache decode step,
Orca-style continuous batching instead of request-at-a-time.

Layers:

- :mod:`kv_cache` — two cache shapes. :class:`KVCache`: fixed-slot
  donated device buffers ``(slots, layers, heads, max_len, head_dim)``.
  :class:`PagedKVCache` (``FLAGS_paged_kv=1``): a shared block pool
  ``(n_blocks, layers, heads, block_size, head_dim)`` + per-slot block
  tables and a host-side free list — slot memory proportional to LIVE
  tokens, admission gated on free blocks instead of a fixed ``max_len``,
  with ``kv_blocks_free`` / ``kv_blocks_used`` / ``kv_fragmentation``
  gauges and loud ``AssertionError`` on free-list double-frees;
- :func:`paddle_tpu.models.gpt_prefill` /
  :func:`paddle_tpu.models.gpt_decode_step` — the cache-aware forward
  variants (they live with the model); paged mode adds
  :func:`~paddle_tpu.models.gpt_prefill_chunk` (one prompt chunk
  appended through the block table) and
  :func:`~paddle_tpu.models.gpt_decode_step_paged`, whose attention is
  the Pallas paged-attention kernel (ops/paged_attention.py) on TPU and
  the identical composed gather elsewhere;
- :mod:`sampling` — fused greedy/temperature/top-k/top-p with per-slot
  parameters;
- :mod:`engine` — the scheduler: bounded queue with backpressure,
  prefill-and-insert admission (paged: CHUNKED prefill, at most
  ``prefill_chunk`` tokens per tick, interleaved with decode so long
  prompts never stall open streams; pool-exhaustion preemption requeues
  the youngest slot), one batched decode step per tick, eviction
  without draining, deadlines/cancellation, graceful shutdown, and the
  serving_* gauges + trace spans.

Escape hatches: ``paddle.set_flags({"FLAGS_serving_jit": 0})`` swaps the
jitted cache path for an un-jitted full-recompute reference decode;
``FLAGS_paged_kv=0`` (default) keeps the fixed-slot cache, pinned
bit-identical to the pre-paging engine.
"""
from .engine import GenerationRequest, InferenceEngine, QueueFull
from .kv_cache import KVCache, PagedKVCache, cache_insert
from .sampling import sample_tokens

__all__ = [
    "InferenceEngine", "GenerationRequest", "QueueFull",
    "KVCache", "PagedKVCache", "cache_insert", "sample_tokens",
]
