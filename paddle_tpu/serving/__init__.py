"""paddle_tpu.serving — continuous-batching inference engine (ISSUE 4).

The generation-side counterpart of ``paddle_tpu.inference``: where the
Predictor serves one compiled program per call (the reference's
AnalysisPredictor shape), this package serves AUTOREGRESSIVE workloads —
many concurrent requests sharing one jitted KV-cache decode step,
Orca-style continuous batching instead of request-at-a-time.

Layers:

- :mod:`kv_cache` — fixed-slot donated device cache
  ``(slots, layers, heads, max_len, head_dim)`` + host-side slot
  accounting;
- :func:`paddle_tpu.models.gpt_prefill` /
  :func:`paddle_tpu.models.gpt_decode_step` — the cache-aware forward
  variants (they live with the model);
- :mod:`sampling` — fused greedy/temperature/top-k/top-p with per-slot
  parameters;
- :mod:`engine` — the scheduler: bounded queue with backpressure,
  prefill-and-insert admission, one batched decode step per tick,
  eviction without draining, deadlines/cancellation, graceful shutdown,
  and the serving_* gauges + trace spans.

Escape hatch: ``paddle.set_flags({"FLAGS_serving_jit": 0})`` swaps the
jitted cache path for an un-jitted full-recompute reference decode.
"""
from .engine import GenerationRequest, InferenceEngine, QueueFull
from .kv_cache import KVCache, cache_insert
from .sampling import sample_tokens

__all__ = [
    "InferenceEngine", "GenerationRequest", "QueueFull",
    "KVCache", "cache_insert", "sample_tokens",
]
