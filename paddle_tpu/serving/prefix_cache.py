"""Radix-tree prefix cache over the paged KV block pool (ISSUE 11).

Production traffic is dominated by SHARED prefixes — one system prompt
plus a few-shot header fanned out across thousands of streams — and the
PR-7 block-table indirection makes sharing them almost free: K/V for
token position p is a pure function of tokens[:p+1], so two requests
whose prompts agree on their first N tokens can point their block
tables at the SAME pool blocks for those positions and only prefill the
tails. This module is the host-side index that finds those blocks: a
radix tree keyed by token-id chunks (one tree level per pool block,
edge label = that block's token chunk), in the style of SGLang's
RadixAttention.

Contract with :class:`~paddle_tpu.serving.kv_cache.PagedKVCache`:

- every tree node owns ONE pool reference on its block
  (``ref_block``), so a cached prefix survives the slot that wrote it;
  a slot that matches the prefix takes its own reference per block
  (``splice``) and releases it at eviction — ``free_slot`` decrements,
  never frees, and a block returns to its shard's free list only when
  the tree AND every reader have let go;
- interior nodes hold FULL ``block_size``-token chunks; a node with a
  shorter chunk is a leaf (the partially-filled last block of some
  prompt). Matching may use any PREFIX of a node's chunk — attention
  masks by position, so a reader attending ``pos < matched`` never
  sees the unmatched tail of a block — but a slot that must WRITE into
  a partially-used shared block first copy-on-write-duplicates it
  (engine ``_cow_jit``), because blocks handed out by the tree are
  read-only to everyone but their original writer;
- a match is capped at ``len(prompt) - 1`` tokens: the engine always
  re-prefills at least the last prompt token, whose logits seed the
  first sampled token (a 100% match would leave nothing to run);
- eviction is LRU-BY-LEAF: only childless nodes whose block has no
  reader beyond the tree itself (pool refcount 1) are reclaimable, in
  least-recently-matched order — refcounts pin everything a live
  stream still reads, and freeing leaves-first keeps every cached
  prefix contiguous from the root. This composes with (does not
  replace) the engine's youngest-first preemption: the scheduler
  reclaims tree leaves BEFORE preempting live work.

The tree is per-shard (``shards=D`` pools partition their blocks), so
a spliced table never crosses the chip boundary the decode step's
gathers assume. All methods run on the engine's single scheduler
thread — like the pool's free lists, this is request-granularity
bookkeeping kept out of the jitted step.

Gauges: ``prefix_matched_tokens`` / ``prefix_lookup_tokens`` feed the
``prefix_hit_rate`` percentage; ``prefix_cache_blocks`` tracks pool
blocks pinned by the tree; ``prefix_evictions`` counts LRU-reclaimed
leaves.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..monitor.stats import (PREFIX_CACHE_BLOCKS, PREFIX_EVICTIONS,
                             PREFIX_HIT_RATE, PREFIX_LOOKUP_TOKENS,
                             PREFIX_MATCHED_TOKENS)

__all__ = ["RadixPrefixCache"]


class _Node:
    """One cached block: ``chunk`` is the token-id tuple its K/V encode
    (full ``block_size`` for interior nodes, shorter only at leaves)."""

    __slots__ = ("chunk", "block", "children", "last_used", "_level")

    def __init__(self, chunk: Tuple[int, ...], block: int):
        self.chunk = chunk
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self._level: Optional[Dict] = None   # the children dict holding us

    def __repr__(self):
        return (f"_Node(block={self.block}, chunk_len={len(self.chunk)}, "
                f"children={len(self.children)})")


def _lcp(chunk: Tuple[int, ...], toks: List[int], start: int,
         limit: int) -> int:
    """Longest common prefix of ``chunk`` and ``toks[start:limit]``."""
    n = min(len(chunk), limit - start)
    i = 0
    while i < n and chunk[i] == toks[start + i]:
        i += 1
    return i


class RadixPrefixCache:
    """Host-side radix index of shared prompt prefixes in a
    :class:`~paddle_tpu.serving.kv_cache.PagedKVCache` pool."""

    def __init__(self, cache):
        self.cache = cache
        self.block_size = int(cache.block_size)
        # per-shard forest: top-level chunk -> node
        self._roots: List[Dict[Tuple[int, ...], _Node]] = [
            {} for _ in range(cache.shards)]
        self._clock = 0          # monotonic touch counter for LRU
        self._blocks = 0         # pool blocks currently pinned by the tree
        # lifetime counters behind the hit-rate gauge
        self._matched = 0
        self._looked_up = 0

    # -- lookup --------------------------------------------------------------
    def match(self, shard: int, tokens) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens`` in ``shard``'s tree.

        Returns ``(matched_len, blocks)``: the first ``matched_len``
        tokens of the prompt are already encoded in ``blocks`` (in table
        order; the last block may be only partially used when
        ``matched_len % block_size != 0`` — the engine CoW-duplicates it
        before the slot extends it). Capped at ``len(tokens) - 1`` so
        the tail prefill always has at least one token to run. Touches
        the matched path for LRU."""
        toks = [int(t) for t in tokens]
        limit = len(toks) - 1
        self._clock += 1
        level = self._roots[shard]
        blocks: List[int] = []
        used = 0
        while used < limit:
            best, best_lcp = None, 0
            for chunk, node in level.items():
                lcp = _lcp(chunk, toks, used, limit)
                if lcp > best_lcp:
                    best, best_lcp = node, lcp
            if best is None:
                break
            blocks.append(best.block)
            used += best_lcp
            best.last_used = self._clock
            if best_lcp < len(best.chunk) or len(best.chunk) < self.block_size:
                break            # partial use, or a leaf chunk — path ends
            level = best.children
        return used, blocks

    def peek(self, shard: int, tokens) -> int:
        """Read-only twin of :meth:`match`: how many leading tokens of
        ``tokens`` the tree could serve right now, WITHOUT touching the
        LRU clock or the hit-rate gauges. The lifecycle re-warm
        verification (and tests) use it to ask "is this prefix warm?"
        without perturbing eviction order. Uncapped — a fully-cached
        prompt peeks at its full length even though ``match`` would
        stop one token short."""
        toks = [int(t) for t in tokens]
        limit = len(toks)
        level = self._roots[shard]
        used = 0
        while used < limit:
            best_lcp = 0
            best = None
            for chunk, node in level.items():
                lcp = _lcp(chunk, toks, used, limit)
                if lcp > best_lcp:
                    best, best_lcp = node, lcp
            if best is None:
                break
            used += best_lcp
            if best_lcp < len(best.chunk) or len(best.chunk) < self.block_size:
                break
            level = best.children
        return used

    def note_lookup(self, matched: int, total: int) -> None:
        """Feed the hit-rate gauge (the engine calls this once per
        admission, with the prompt length it looked up)."""
        self._matched += int(matched)
        self._looked_up += int(total)
        PREFIX_MATCHED_TOKENS.add(int(matched))
        PREFIX_LOOKUP_TOKENS.add(int(total))
        if self._looked_up > 0:
            PREFIX_HIT_RATE.set(
                int(round(100.0 * self._matched / self._looked_up)))

    # -- insertion -----------------------------------------------------------
    def insert(self, shard: int, tokens, table: Sequence[int]) -> int:
        """Register a fully-prefilled prompt: walk ``tokens`` in
        block-size chunks, adopting ``table``'s blocks for chunks the
        tree does not hold yet (one tree reference each). Existing
        chunks are touched, not replaced — the first writer wins, later
        identical prompts keep their private blocks (their content is
        identical anyway; LRU reclaims the duplicates). Returns the
        number of blocks newly adopted."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        self._clock += 1
        level = self._roots[shard]
        adopted = 0
        for i in range(0, len(toks), bs):
            chunk = tuple(toks[i:i + bs])
            node = level.get(chunk)
            if node is None:
                node = _Node(chunk, int(table[i // bs]))
                self.cache.ref_block(node.block)
                node._level = level      # the dict holding us (for evict)
                level[chunk] = node
                adopted += 1
                self._blocks += 1
            node.last_used = self._clock
            if len(chunk) < bs:
                break                    # partial tail chunk is a leaf
            level = node.children
        if adopted:
            PREFIX_CACHE_BLOCKS.set(self._blocks)
            self.cache.update_gauges()
        return adopted

    # -- eviction ------------------------------------------------------------
    def evictable_count(self, shard: int) -> int:
        """Blocks LRU eviction could return to ``shard``'s free list
        right now (childless nodes nobody reads but the tree). Interior
        nodes become evictable as their leaves go, so this undercounts
        the full reclaimable depth — the admission gate only needs a
        lower bound."""
        return sum(1 for _ in self._iter_evictable(shard))

    def _iter_evictable(self, shard: int):
        stack = list(self._roots[shard].values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.cache.ref_count(node.block) == 1:
                yield node

    def evict(self, shard: int, n_blocks: int) -> int:
        """Reclaim up to ``n_blocks`` pool blocks from ``shard``'s tree,
        least-recently-matched leaves first (a freed leaf can expose its
        parent as the next candidate). Returns how many blocks actually
        went back to the shard's free list."""
        freed = 0
        while freed < n_blocks:
            victim = None
            for node in self._iter_evictable(shard):
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            del victim._level[victim.chunk]
            self.cache.unref_block(victim.block)
            self._blocks -= 1
            freed += 1
        if freed:
            PREFIX_EVICTIONS.add(freed)
            PREFIX_CACHE_BLOCKS.set(self._blocks)
            self.cache.update_gauges()
        return freed

    # -- introspection -------------------------------------------------------
    @property
    def block_count(self) -> int:
        return self._blocks

    @property
    def hit_rate(self) -> float:
        return self._matched / self._looked_up if self._looked_up else 0.0

    def __repr__(self):
        return (f"RadixPrefixCache(blocks={self._blocks}, "
                f"hit_rate={self.hit_rate:.2f})")
