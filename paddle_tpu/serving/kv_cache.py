"""Fixed-slot KV cache for the serving engine.

The reference's inference stack keeps per-predictor scratch memory alive
across runs (AnalysisPredictor zero-copy tensors); the autoregressive
analog is the decode cache. This one is Orca/vLLM-slot style, TPU-shaped:
ONE pair of device buffers

    k, v : (n_slots, n_layers, n_heads, max_len, head_dim)   cfg.dtype

allocated once and donated through every jitted prefill/decode call, so
steady-state serving allocates nothing and the compiled decode program
has a single static shape regardless of which slots are live. A slot is
the unit of admission: a request owns exactly one slot from prefill to
eviction; per-slot write positions and attention masks come from the
``positions`` argument of :func:`paddle_tpu.models.gpt_decode_step`, so
slots at different generation depths batch into one program.

Slot bookkeeping (free list, per-slot length) is host-side — it changes
at request granularity, not token granularity, and keeping it out of the
device state keeps the decode step free of host syncs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..monitor.stats import (KV_BLOCKS_FREE, KV_BLOCKS_USED,
                             KV_FRAGMENTATION)

__all__ = ["KVCache", "PagedKVCache", "cache_insert"]


def cache_insert(k_cache, v_cache, slot, k_new, v_new):
    """Write one sequence's prefill entries into a slot.

    k_new/v_new: (L, nh, S, hd) with S <= max_len (gpt_prefill output for
    one sequence); ``slot`` may be traced — one compiled insert serves
    every slot. Positions >= S keep whatever they held; decode overwrites
    position S, S+1, ... before ever attending to them."""
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_new[None].astype(k_cache.dtype), (slot, 0, 0, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_new[None].astype(v_cache.dtype), (slot, 0, 0, 0, 0))
    return k_cache, v_cache


class KVCache:
    """Slotted decode cache: device buffers + host-side slot accounting."""

    def __init__(self, cfg, n_slots: int, max_len: Optional[int] = None,
                 dtype=None):
        if max_len is None:
            max_len = cfg.seq_len
        if max_len > cfg.seq_len:
            raise ValueError(
                f"max_len={max_len} exceeds the model's positional table "
                f"(cfg.seq_len={cfg.seq_len})")
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.dtype = cfg.dtype if dtype is None else dtype
        shape = (self.n_slots, cfg.n_layers, cfg.n_heads, self.max_len,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # host-side per-slot token counts (== next write position)
        self.lengths = np.zeros(self.n_slots, np.int32)
        self._free: List[int] = list(range(self.n_slots))

    # -- slot accounting -----------------------------------------------------
    def alloc(self) -> Optional[int]:
        """Claim a free slot (None when full). Contents are whatever the
        previous occupant left — prefill overwrites them."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self.lengths[slot] = 0
        return slot

    def release(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.lengths[slot] = 0
        self._free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    def __repr__(self):
        return (f"KVCache(slots={self.n_slots}, max_len={self.max_len}, "
                f"occupied={self.occupancy}, {self.nbytes / 1e6:.1f}MB)")


class PagedKVCache:
    """Paged decode cache (FLAGS_paged_kv, ISSUE 7): a shared block pool
    plus per-slot block tables — vLLM-style PagedAttention memory, TPU
    shaped.

    Device side: ONE pair of donated pool buffers

        kb, vb : (n_blocks, n_layers, n_heads, block_size, head_dim)

    Unlike :class:`KVCache`, a slot does not own a contiguous max_len
    strip — it owns however many ``block_size``-token blocks its prompt
    and generation have actually filled, named in order by its block
    table. Cache memory is therefore proportional to LIVE tokens, and a
    prompt is admissible whenever enough free blocks exist, regardless
    of any per-slot length budget (up to ``cfg.seq_len``, the positional
    table).

    The first block of each shard range is RESERVED as that shard's
    garbage sink: it is never allocated, table padding entries (and the
    sink-filled tables of unoccupied batch lanes) point at it, so the
    batched decode step's stale-lane scatter writes land where no live
    slot ever reads. With the default ``shards=1`` that is pool block 0,
    exactly the ISSUE-7 layout.

    Multi-chip layout (ISSUE 10, ``shards=D``): the pool is partitioned
    into D contiguous shard ranges so the device buffers can shard over
    the mesh "data" axis — shard d owns blocks ``[d*per, (d+1)*per)``,
    slot s belongs to shard ``s // (n_slots // D)``, and a slot only
    ever allocates (and sinks its garbage) inside its OWN shard's range,
    so every block-table lookup, scatter and gather in the decode step
    stays local to the chip holding that slot's lane. Free lists are
    per-shard; admission asks :meth:`admit_shard` for the shard that can
    host a request (free slot + enough free blocks, most-free wins).

    Host side: the free lists, per-slot tables and lengths — request/
    block-granularity bookkeeping kept out of the jitted step, exactly
    like KVCache's slot accounting. Double-frees in the block free list
    raise ``AssertionError`` (a corrupted free list silently cross-wires
    two requests' caches — fail loudly instead). The pool exports
    ``kv_blocks_free`` / ``kv_blocks_used`` gauges and a
    ``kv_fragmentation`` percentage (share of used-block capacity not
    holding a live token) through the StatRegistry, aggregated over
    shards.

    Refcounted sharing (ISSUE 11, the radix prefix cache): every
    allocated block carries a reference count. ``grow``/``alloc_block``
    hand out blocks at refcount 1; :meth:`ref_block` lets another owner
    (a second slot's table, or the prefix tree itself) pin the same
    block, and releasing a table *unrefs* instead of freeing — a block
    only returns to its shard's free list when the LAST reference drops
    (``free_slot``-decrements-instead-of-freeing is what lets one
    prefilled system prompt fan out under thousands of streams).
    Writers never mutate a shared block: a slot that must extend a
    partially-filled shared block first :meth:`replace_block`\\ s it
    with a copy-on-write duplicate (the device-side copy is the
    engine's one-compile ``_cow_jit`` program). ``kv_fragmentation``
    counts each pool block's capacity once however many slots read it,
    so heavy sharing legitimately drives the gauge toward 0.
    """

    def __init__(self, cfg, n_slots: int, n_blocks: Optional[int] = None,
                 block_size: int = 16, dtype=None, shards: int = 1):
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be >= 1")
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.block_size = int(block_size)
        self.shards = int(shards)
        if self.shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if self.n_slots % self.shards != 0:
            raise ValueError(f"n_slots={n_slots} not divisible by "
                             f"shards={shards}")
        # widest table any slot can need: the positional table is the
        # per-slot length ceiling
        self.table_width = -(-cfg.seq_len // self.block_size)
        if n_blocks is None:
            # worst case every slot runs to seq_len, +1 sink per shard
            n_blocks = self.shards + self.n_slots * self.table_width
        self.n_blocks = int(n_blocks)
        if self.n_blocks % self.shards != 0:
            raise ValueError(f"n_blocks={self.n_blocks} not divisible by "
                             f"shards={shards}")
        self.blocks_per_shard = self.n_blocks // self.shards
        if self.blocks_per_shard < 2:
            raise ValueError(
                f"n_blocks={self.n_blocks} must give every shard >= 2 "
                "blocks (the first block of each shard range is its "
                "reserved garbage sink)")
        self.dtype = cfg.dtype if dtype is None else dtype
        shape = (self.n_blocks, cfg.n_layers, cfg.n_heads, self.block_size,
                 cfg.head_dim)
        self.kb = jnp.zeros(shape, self.dtype)
        self.vb = jnp.zeros(shape, self.dtype)
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.block_tables: List[List[int]] = [[] for _ in range(self.n_slots)]
        # per-shard free lists; the first block of each range is the sink
        self._free: List[List[int]] = [
            list(range(d * self.blocks_per_shard + 1,
                       (d + 1) * self.blocks_per_shard))
            for d in range(self.shards)]
        self._free_set = set(b for free in self._free for b in free)
        self._refs: dict = {}      # allocated block -> reference count
        self._slot_free: List[int] = list(range(self.n_slots))
        self._update_gauges()

    # -- shard topology ------------------------------------------------------
    @property
    def slots_per_shard(self) -> int:
        return self.n_slots // self.shards

    def shard_of(self, slot: int) -> int:
        return slot // self.slots_per_shard

    def sink_of(self, shard: int) -> int:
        return shard * self.blocks_per_shard

    @property
    def max_slot_blocks(self) -> int:
        """Largest block count one slot can ever own (its shard's pool
        minus the sink) — the submit-time can-never-fit bound."""
        return self.blocks_per_shard - 1

    # -- slot accounting (same surface as KVCache) ---------------------------
    def alloc(self, prefer_shard: Optional[int] = None) -> Optional[int]:
        if not self._slot_free:
            return None
        if prefer_shard is not None:
            for i, s in enumerate(self._slot_free):
                if self.shard_of(s) == prefer_shard:
                    slot = self._slot_free.pop(i)
                    break
            else:
                return None
        else:
            slot = self._slot_free.pop(0)
        self.lengths[slot] = 0
        self.block_tables[slot] = []
        return slot

    def release(self, slot: int) -> None:
        if slot in self._slot_free:
            raise ValueError(f"slot {slot} is already free")
        self.free_blocks(self.block_tables[slot])
        self.block_tables[slot] = []
        self.lengths[slot] = 0
        self._slot_free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._slot_free)

    @property
    def occupancy(self) -> int:
        return self.n_slots - len(self._slot_free)

    # -- block accounting ----------------------------------------------------
    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def can_admit(self, n_tokens: int) -> bool:
        """Some shard has enough free blocks to cache ``n_tokens``? (The
        admission gate — replaces the fixed engine's ``prompt >=
        max_len`` hard reject; pair with :meth:`admit_shard` to also
        require a free slot in that shard.)"""
        need = self.blocks_for(n_tokens)
        return any(need <= len(free) for free in self._free)

    def admit_shard(self, n_tokens: int) -> Optional[int]:
        """The shard that should host a new request needing ``n_tokens``
        cached: a free slot AND enough free blocks, most free blocks
        wins (keeps shard load balanced). None when no shard qualifies."""
        need = self.blocks_for(n_tokens)
        free_slots = {self.shard_of(s) for s in self._slot_free}
        best = None
        for d in range(self.shards):
            if d in free_slots and need <= len(self._free[d]):
                if best is None or len(self._free[d]) > len(self._free[best]):
                    best = d
        return best

    @property
    def free_slot_shards(self) -> set:
        """Shards that currently have at least one free slot."""
        return {self.shard_of(s) for s in self._slot_free}

    @property
    def free_blocks_count(self) -> int:
        return sum(len(free) for free in self._free)

    @property
    def used_blocks_count(self) -> int:
        return self.n_blocks - self.shards - self.free_blocks_count

    def free_blocks_of(self, shard: int) -> int:
        return len(self._free[shard])

    def grow(self, slot: int, n_tokens: int) -> bool:
        """Extend ``slot``'s table to cover positions < n_tokens, from
        its OWN shard's free list. All-or-nothing: returns False
        (allocating nothing) when that list cannot supply every needed
        block. Fresh blocks start at refcount 1 (this table)."""
        need = self.blocks_for(n_tokens)
        table = self.block_tables[slot]
        extra = need - len(table)
        if extra <= 0:
            return True
        free = self._free[self.shard_of(slot)]
        if extra > len(free):
            return False
        for _ in range(extra):
            b = free.pop(0)
            self._free_set.discard(b)
            self._refs[b] = 1
            table.append(b)
        self._update_gauges()
        return True

    def alloc_block(self, shard: int) -> Optional[int]:
        """One free block from ``shard``'s list at refcount 1 (the
        copy-on-write destination), or None when the shard is dry."""
        free = self._free[shard]
        if not free:
            return None
        b = free.pop(0)
        self._free_set.discard(b)
        self._refs[b] = 1
        self._update_gauges()
        return b

    def ref_block(self, block: int) -> None:
        """Pin one more reference on an allocated block (a second slot's
        table, or the prefix tree adopting it)."""
        b = int(block)
        if b not in self._refs:
            raise AssertionError(
                f"KV block {b} ref'd while not allocated (use-after-free)")
        self._refs[b] += 1

    def ref_count(self, block: int) -> int:
        return self._refs.get(int(block), 0)

    def unref_block(self, block: int) -> None:
        """Drop one reference; the LAST drop returns the block to its
        shard's free list (this is ``free_slot`` decrementing instead of
        freeing — shared prefix blocks survive their first owner)."""
        b = int(block)
        if b in self._free_set:
            raise AssertionError(
                f"KV block {b} double-freed (free-list corruption)")
        shard, local = divmod(b, self.blocks_per_shard)
        if not 0 <= shard < self.shards or local == 0:
            raise AssertionError(f"KV block {b} outside pool or a "
                                 "reserved shard sink")
        refs = self._refs.get(b)
        if refs is None:
            raise AssertionError(
                f"KV block {b} unref'd while not allocated "
                "(refcount corruption)")
        if refs > 1:
            self._refs[b] = refs - 1
            return
        del self._refs[b]
        self._free[shard].append(b)
        self._free_set.add(b)

    def free_blocks(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.unref_block(b)
        self._update_gauges()

    def splice(self, slot: int, blocks: Sequence[int]) -> None:
        """Seed an empty slot table with already-allocated (shared)
        blocks, taking one reference per block — the prefix-cache hit
        path. Blocks must belong to the slot's shard (the decode
        step's lookups stay chip-local)."""
        table = self.block_tables[slot]
        if table:
            raise AssertionError(
                f"splice into slot {slot} with a non-empty table")
        shard = self.shard_of(slot)
        for b in blocks:
            if int(b) // self.blocks_per_shard != shard:
                raise AssertionError(
                    f"KV block {b} spliced across shards "
                    f"(slot {slot} is shard {shard})")
            self.ref_block(b)
            table.append(int(b))
        self._update_gauges()

    def replace_block(self, slot: int, index: int, new_block: int) -> int:
        """Swap one table entry for ``new_block`` (the copy-on-write
        commit: the caller has already device-copied the old block's
        rows into ``new_block`` via the engine's cow program). Drops
        this table's reference on the old block and returns it."""
        table = self.block_tables[slot]
        old = table[index]
        table[index] = int(new_block)
        self.unref_block(old)
        self._update_gauges()
        return old

    def table_row(self, slot: int) -> np.ndarray:
        """This slot's table as a fixed-width int32 row, sink-padded
        (with the slot's OWN shard sink, so padding lookups stay
        shard-local)."""
        row = np.full(self.table_width,
                      self.sink_of(self.shard_of(slot)), np.int32)
        table = self.block_tables[slot]
        row[:len(table)] = table
        return row

    def tables_array(self, slots=None) -> np.ndarray:
        """(n_slots, table_width) int32 for the batched decode step; rows
        not in ``slots`` (and all padding) point at their shard's
        garbage sink."""
        out = np.empty((self.n_slots, self.table_width), np.int32)
        for s in range(self.n_slots):
            out[s] = self.sink_of(self.shard_of(s))
        for s in (range(self.n_slots) if slots is None else slots):
            table = self.block_tables[s]
            out[s, :len(table)] = table
        return out

    # -- gauges --------------------------------------------------------------
    def _update_gauges(self) -> None:
        used = self.used_blocks_count
        KV_BLOCKS_FREE.set(self.free_blocks_count)
        KV_BLOCKS_USED.set(used)
        cap = used * self.block_size
        live = int(self.lengths.sum())
        KV_FRAGMENTATION.set(
            0 if cap == 0 else int(round(100.0 * (1.0 - min(1.0, live / cap)))))

    update_gauges = _update_gauges

    @property
    def nbytes(self) -> int:
        return int(self.kb.nbytes) + int(self.vb.nbytes)

    def __repr__(self):
        return (f"PagedKVCache(slots={self.n_slots}, "
                f"blocks={self.n_blocks}x{self.block_size}, "
                f"shards={self.shards}, "
                f"used={self.used_blocks_count}, occupied={self.occupancy}, "
                f"{self.nbytes / 1e6:.1f}MB)")
