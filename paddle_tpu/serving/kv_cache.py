"""Fixed-slot KV cache for the serving engine.

The reference's inference stack keeps per-predictor scratch memory alive
across runs (AnalysisPredictor zero-copy tensors); the autoregressive
analog is the decode cache. This one is Orca/vLLM-slot style, TPU-shaped:
ONE pair of device buffers

    k, v : (n_slots, n_layers, n_heads, max_len, head_dim)   cfg.dtype

allocated once and donated through every jitted prefill/decode call, so
steady-state serving allocates nothing and the compiled decode program
has a single static shape regardless of which slots are live. A slot is
the unit of admission: a request owns exactly one slot from prefill to
eviction; per-slot write positions and attention masks come from the
``positions`` argument of :func:`paddle_tpu.models.gpt_decode_step`, so
slots at different generation depths batch into one program.

Slot bookkeeping (free list, per-slot length) is host-side — it changes
at request granularity, not token granularity, and keeping it out of the
device state keeps the decode step free of host syncs.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCache", "cache_insert"]


def cache_insert(k_cache, v_cache, slot, k_new, v_new):
    """Write one sequence's prefill entries into a slot.

    k_new/v_new: (L, nh, S, hd) with S <= max_len (gpt_prefill output for
    one sequence); ``slot`` may be traced — one compiled insert serves
    every slot. Positions >= S keep whatever they held; decode overwrites
    position S, S+1, ... before ever attending to them."""
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_new[None].astype(k_cache.dtype), (slot, 0, 0, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_new[None].astype(v_cache.dtype), (slot, 0, 0, 0, 0))
    return k_cache, v_cache


class KVCache:
    """Slotted decode cache: device buffers + host-side slot accounting."""

    def __init__(self, cfg, n_slots: int, max_len: Optional[int] = None,
                 dtype=None):
        if max_len is None:
            max_len = cfg.seq_len
        if max_len > cfg.seq_len:
            raise ValueError(
                f"max_len={max_len} exceeds the model's positional table "
                f"(cfg.seq_len={cfg.seq_len})")
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.dtype = cfg.dtype if dtype is None else dtype
        shape = (self.n_slots, cfg.n_layers, cfg.n_heads, self.max_len,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # host-side per-slot token counts (== next write position)
        self.lengths = np.zeros(self.n_slots, np.int32)
        self._free: List[int] = list(range(self.n_slots))

    # -- slot accounting -----------------------------------------------------
    def alloc(self) -> Optional[int]:
        """Claim a free slot (None when full). Contents are whatever the
        previous occupant left — prefill overwrites them."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self.lengths[slot] = 0
        return slot

    def release(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.lengths[slot] = 0
        self._free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    def __repr__(self):
        return (f"KVCache(slots={self.n_slots}, max_len={self.max_len}, "
                f"occupied={self.occupancy}, {self.nbytes / 1e6:.1f}MB)")
