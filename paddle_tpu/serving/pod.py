"""Cross-host serving fleet with disaggregated prefill/decode (ISSUE 19).

The PR-13/14 serving stack — :class:`~.router.EngineRouter` affinity +
token-replay failover, :class:`~.lifecycle.ReplicaSupervisor`, the
overload ladder, the HTTP frontend — tops out at one Python process,
because every replica is an in-process :class:`~.engine.InferenceEngine`.
This module carries the SAME replica protocol across hosts:

- :class:`FleetRegistry` — host registration/heartbeat records over the
  elastic :class:`~paddle_tpu.distributed.elastic.FileKVStore` the
  trainers already use (binary-framed ``put_bytes`` records, the same
  put-retry + partition tolerance, and the monotonic payload-change
  staleness discipline of ``ElasticManager.alive_hosts`` — wall-clock
  skew between hosts cannot kill a live one).
- :class:`HostAgent` — runs on each host: owns that host's engines,
  serves them over a :class:`~.rpc.RpcServer` (submit / long-poll wait /
  adopt / health / KV export + import / ensure_replicas), heartbeats the
  registry.
- :class:`RemoteReplica` — the client-side proxy. It exposes the
  in-process engine surface (``submit``/``adopt_request``/``alive``/
  ``tick_age``/``pool_headroom``/``warm_prefix``/…), so EngineRouter,
  ReplicaSupervisor and the frontend compose UNCHANGED. Each submitted
  request gets a local :class:`~.engine.GenerationRequest` mirror fed by
  a per-request pump thread long-polling the host; a transport death
  finishes the mirror with ``error``, which fires the router failover
  hook — the PR-13 token-identical replay adoption, now across hosts.
- :class:`FleetRouter` — an EngineRouter that also: watches the registry
  and turns a lost host into immediate re-routes of its open streams
  (``fleet_reroutes``); offers returned hosts to the supervisor's
  per-(host, replica) quarantine ladder (``note_host_offer``); and runs
  the **disaggregated submit path**: long prompts prefill on a
  prefill-ROLE replica, whose finished KV blocks stream back (serialized
  pool rows, bf16-safe over the RPC blob channel) and splice into the
  chosen decode replica's radix tree via the refcounted block machinery
  — so a plain ``submit`` then hits the prefix cache and decode ticks
  never stall on a long prompt. Identity rides the pinned prefix-splice
  guarantee: streamed-KV output is token-identical to a monolithic
  engine, greedy and sampled.
- :class:`ArrivalRateForecaster` / :class:`FleetScheduler` — assigns
  roles, sizes pools per phase, and pre-warms decode replicas from the
  measured arrival rate (``fleet_arrival_gap_ms``) instead of reacting
  to brownout rungs after the storm arrives.

Per-host flight-recorder dumps are named by host (monitor/flight.py), so
``tools/trace_report.py`` ``merge_traces`` stitches a fleet incident
into one timeline; the new ``fleet`` section reads the spans this module
emits (``fleet.members`` / ``fleet.kv_stream`` / ``fleet.direct`` /
``fleet.host_lost`` / ``fleet.prewarm``).

Locking (GL003/GL004): registry state under ``FleetRegistry._lock``,
agent request-registry under ``HostAgent._lock``, proxy open-stream map
and health cache under ``RemoteReplica._lock``, fleet host sets under
``FleetRouter._fleet_lock`` — and no method calls out of the module
while holding any of them, so no ordering cycle with the router lock or
a request's condition variable is possible.
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..monitor.flight import dump_flight, get_flight_recorder
from ..monitor.stats import (FLEET_ARRIVAL_GAP_MS, FLEET_DIRECT_FALLBACKS,
                             FLEET_HOSTS, FLEET_KV_CHUNKS_STREAMED,
                             FLEET_KV_EXPORTS, FLEET_KV_IMPORTS,
                             FLEET_KV_RESUME_TAILS, FLEET_KV_TRANSFER_BYTES,
                             FLEET_KV_TRANSFER_MS, FLEET_PREFILL_ROUTED,
                             FLEET_PREWARMS, FLEET_REPLICAS, FLEET_REROUTES,
                             FLIGHT_COLLECTS, stat_snapshot)
from ..monitor.trace import emit_complete, recording
from .engine import ERROR, LENGTH, GenerationRequest, QueueFull
from .router import EngineRouter
from .rpc import (BREAKER_OPEN, CircuitBreaker, RetryPolicy, RpcClient,
                  RpcError, RpcRemoteError, RpcServer)

__all__ = ["FleetRegistry", "HostAgent", "RemoteReplica",
           "RemoteReplicaError", "FleetRouter", "FleetScheduler",
           "ArrivalRateForecaster", "connect_fleet"]


class RemoteReplicaError(RuntimeError):
    """Carried as a mirrored request's ``error`` when the remote side
    failed it (or its host stopped answering) — the failover trigger."""


# ===========================================================================
# registry
# ===========================================================================
class FleetRegistry:
    """Host registration/heartbeat over the shared FileKVStore.

    Records live under ``fleet/<job>/hosts/<host>`` as framed binary
    JSON (:meth:`FileKVStore.put_bytes` — checksummed, so a torn NFS
    read is detected, never consumed). Liveness follows the elastic
    trainers' discipline: a host is alive while its record PAYLOAD keeps
    changing within ``ttl`` seconds of this observer's monotonic clock —
    each heartbeat bumps a ``seq`` counter, so identical-payload
    staleness cannot false-positive, and wall-clock skew is irrelevant.
    """

    def __init__(self, store, job: str, ttl: float = 2.0):
        self.store = store
        self.job = str(job)
        self.ttl = float(ttl)
        self._lock = threading.Lock()      # guards _seen
        self._seen: Dict[str, tuple] = {}  # host -> (payload, first_mono)

    def _key(self, host: str) -> str:
        return f"fleet/{self.job}/hosts/{host}"

    def _dir(self) -> str:
        return f"fleet/{self.job}/hosts/"

    def announce(self, host: str, record: dict) -> None:
        """Write/refresh a host's record (put-retry rides along; an
        OSError after the retry budget means partition — callers skip
        the beat and try again)."""
        self.store.put_bytes(self._key(host),
                             json.dumps(record, sort_keys=True).encode())

    def retire(self, host: str) -> None:
        """Graceful deregistration (host loss is the OTHER path: the
        record simply stops changing and ages out)."""
        self.store.delete(self._key(host))

    def alive(self) -> Dict[str, dict]:
        """{host: record} for every host whose record changed within
        ``ttl``. Raises OSError under an injected/real partition — the
        fleet monitor skips that scan rather than declaring hosts dead
        on a blind round."""
        listed = self.store.get_prefix(self._dir())
        now = time.monotonic()
        out: Dict[str, dict] = {}
        for key in listed:
            host = key.rsplit("/", 1)[-1]
            try:
                payload = self.store.get_bytes(self._key(host))
            except ValueError:
                continue                   # torn frame: miss one round
            if payload is None:
                continue
            with self._lock:
                prev = self._seen.get(host)
                if prev is None or prev[0] != payload:
                    self._seen[host] = (payload, now)
                    fresh = True
                else:
                    fresh = (now - prev[1]) <= self.ttl
            if fresh:
                try:
                    out[host] = json.loads(payload)
                except (ValueError, UnicodeDecodeError):
                    continue
        return out


# ===========================================================================
# host agent (server side)
# ===========================================================================
class HostAgent:
    """One per host: owns the host's engines and serves the replica
    protocol over RPC.

    ``factory()`` builds one engine (same config/params/seed on every
    host — the sameness that makes cross-host failover exact, identical
    to the in-process router contract). ``role`` is ``"prefill"``,
    ``"decode"`` or ``"mixed"`` and rides the registry record so
    :func:`connect_fleet` can wire the disaggregated path.
    """

    def __init__(self, store, job: str, host: str, factory,
                 n_replicas: int = 1, role: str = "mixed",
                 listen_host: str = "127.0.0.1", port: int = 0,
                 heartbeat_s: float = 0.25, registry_ttl: float = 2.0):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"unknown fleet role {role!r}")
        self.host = str(host)
        self.role = role
        self.factory = factory
        self.heartbeat_s = float(heartbeat_s)
        self._lock = threading.Lock()      # guards _engines/_reqs/_hseq/_seq
        self._engines: List[object] = []
        self._reqs: Dict[int, GenerationRequest] = {}
        self._hseq = 0
        self._seq = 0
        for _ in range(max(1, int(n_replicas))):
            self._spawn_engine()
        self._server = RpcServer(self._handlers(), host=listen_host,
                                 port=port)
        self.addr = self._server.addr
        self.registry = FleetRegistry(store, job, ttl=registry_ttl)
        self._closed_event = threading.Event()
        self.announce()                    # visible before the first beat
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    name="fleet-heartbeat", daemon=True)
        self._hb.start()

    # -- engines -------------------------------------------------------------
    def _spawn_engine(self):
        eng = self.factory()
        eng.host = self.host               # satellite 3: ladder re-key +
        eng.role = self.role               # fleet membership surface
        with self._lock:
            self._engines.append(eng)
        return eng

    def _engine(self, idx: int):
        with self._lock:
            try:
                return self._engines[int(idx)]
            except IndexError:
                raise KeyError(f"host {self.host} has no replica "
                               f"index {idx}") from None

    def _describe(self) -> List[dict]:
        with self._lock:
            engines = list(self._engines)
        out = []
        for i, e in enumerate(engines):
            out.append({"idx": i, "block_size": int(e.block_size),
                        "prefill_chunk": int(e.prefill_chunk),
                        "n_slots": int(e.n_slots),
                        "max_len": int(e.max_len),
                        "vocab_size": int(e.cfg.vocab_size),
                        "prefix": getattr(e, "_prefix", None) is not None,
                        "tokenizer": type(e.tokenizer).__name__
                        if getattr(e, "tokenizer", None) is not None
                        else None})
        return out

    # -- registry heartbeat --------------------------------------------------
    def announce(self) -> None:
        with self._lock:
            self._seq += 1
            record = {"host": self.host, "role": self.role,
                      "addr": list(self.addr),
                      "replicas": len(self._engines), "seq": self._seq}
        try:
            self.registry.announce(self.host, record)
        except OSError:
            pass                           # partition: next beat retries

    def _heartbeat_loop(self) -> None:
        while not self._closed_event.wait(self.heartbeat_s):
            self.announce()

    # -- request registry ----------------------------------------------------
    def _register(self, req: GenerationRequest) -> int:
        with self._lock:
            self._hseq += 1
            hid = self._hseq
            self._reqs[hid] = req
        return hid

    def _req(self, hid: int) -> GenerationRequest:
        with self._lock:
            req = self._reqs.get(int(hid))
        if req is None:
            raise KeyError(f"unknown or finished request handle {hid}")
        return req

    # -- rpc handlers --------------------------------------------------------
    def _handlers(self) -> dict:
        return {"hello": self._h_hello, "submit": self._h_submit,
                "wait": self._h_wait, "cancel": self._h_cancel,
                "adopt": self._h_adopt, "health": self._h_health,
                "warm": self._h_warm,
                "prefill_export": self._h_prefill_export,
                "import_kv": self._h_import_kv,
                "prefill_start": self._h_prefill_start,
                "export_range": self._h_export_range,
                "import_chunk": self._h_import_chunk,
                "collect_flight": self._h_collect_flight,
                "ensure_replicas": self._h_ensure_replicas,
                "evacuate": self._h_evacuate,
                "fail_replica": self._h_fail_replica,
                "shutdown_replica": self._h_shutdown_replica}

    def _h_hello(self, p, arrays):
        return {"host": self.host, "role": self.role,
                "replicas": self._describe()}

    def _h_submit(self, p, arrays):
        eng = self._engine(p["idx"])
        req = eng.submit(
            prompt=arrays["prompt"],
            max_new_tokens=int(p.get("max_new_tokens", 32)),
            temperature=float(p.get("temperature", 0.0)),
            top_k=int(p.get("top_k", 0)), top_p=float(p.get("top_p", 1.0)),
            eos_id=p.get("eos_id"), deadline_s=p.get("deadline_s"),
            block=bool(p.get("block", True)), timeout=p.get("timeout"))
        return {"hid": self._register(req), "rid": int(req.rid)}

    def _h_adopt(self, p, arrays):
        eng = self._engine(p["idx"])
        deadline = p.get("deadline_s")
        req = GenerationRequest(
            arrays["prompt"], int(p.get("max_new_tokens", 32)),
            float(p.get("temperature", 0.0)), int(p.get("top_k", 0)),
            float(p.get("top_p", 1.0)), p.get("eos_id"),
            None if deadline is None else time.monotonic() + deadline)
        req.rid = int(p["rid"])
        req.tokens = [int(t) for t in p.get("tokens", ())]
        eng.adopt_request(req)
        return {"hid": self._register(req), "rid": int(req.rid)}

    def _h_wait(self, p, arrays):
        hid = int(p["hid"])
        req = self._req(hid)
        cursor = int(p.get("cursor", 0))
        timeout = float(p.get("timeout", 1.0))
        with req._cv:
            req._cv.wait_for(lambda: len(req.tokens) > cursor
                             or req.finish_reason is not None, timeout)
            fresh = [int(t) for t in req.tokens[cursor:]]
            reason = req.finish_reason
            err = req.error
        done = reason is not None
        if done:
            with self._lock:               # one done report retires the
                self._reqs.pop(hid, None)  # handle — no registry leak
        return {"tokens": fresh, "done": done, "finish_reason": reason,
                "error": None if err is None
                else f"{type(err).__name__}: {err}"}

    def _h_cancel(self, p, arrays):
        try:
            self._req(int(p["hid"])).cancel()
        except KeyError:
            pass                           # already finished: cancel is moot
        return {"ok": True}

    def _h_health(self, p, arrays):
        eng = self._engine(p.get("idx", 0))
        return {"alive": bool(eng.alive), "busy": bool(eng.busy),
                "tick_age_s": float(eng.tick_age()),
                "pool_headroom": float(eng.pool_headroom()),
                "queue_depth": int(eng.queue_depth),
                "occupancy": int(eng.occupancy)}

    def _h_warm(self, p, arrays):
        eng = self._engine(p["idx"])
        eng.warm_prefix(arrays["prompt"]).result(
            timeout=p.get("timeout", 120.0))
        return {"ok": True}

    def _h_prefill_export(self, p, arrays):
        """Chunked-prefill the prompt (radix-warm, dedup against what the
        tree already holds) and ship the finished KV blocks."""
        eng = self._engine(p["idx"])
        ids = np.asarray(arrays["prompt"], np.int32).reshape(-1)
        if getattr(eng, "_prefix", None) is None:
            raise RuntimeError("prefill export needs prefix_cache=True")
        have = eng.run_on_scheduler(
            lambda e: max(e._prefix.peek(d, ids)
                          for d in range(e.cache.shards)))
        if have < ids.size - 1:
            eng.warm_prefix(ids).result(timeout=p.get("timeout", 120.0))
        exp = eng.export_kv_prefix(ids)
        if exp is None:
            return {"matched_len": 0}
        FLEET_KV_EXPORTS.add(1)
        meta = {"matched_len": exp["matched_len"],
                "block_size": exp["block_size"], "dtype": exp["dtype"]}
        return meta, {"kb": exp["kb"], "vb": exp["vb"]}

    def _h_import_kv(self, p, arrays):
        eng = self._engine(p["idx"])
        cached = eng.import_kv_prefix(arrays["prompt"], arrays["kb"],
                                      arrays["vb"],
                                      int(p["matched_len"]))
        if cached > 0:
            FLEET_KV_IMPORTS.add(1)
        return {"cached": int(cached)}

    # -- resumable chunked KV streaming (ISSUE 20) ---------------------------
    def _h_prefill_start(self, p, arrays):
        """Kick off a NON-blocking radix warm of the prompt so finished
        chunks can ship (``export_range``) while later chunks compute —
        the overlap half of resumable streaming. Returns the stream
        target (``len-1``, the splice cap) and what is already cached."""
        eng = self._engine(p["idx"])
        ids = np.asarray(arrays["prompt"], np.int32).reshape(-1)
        if getattr(eng, "_prefix", None) is None:
            raise RuntimeError("prefill streaming needs prefix_cache=True")
        have = eng.run_on_scheduler(
            lambda e: max(e._prefix.peek(d, ids)
                          for d in range(e.cache.shards)))
        if have < ids.size - 1:
            eng.warm_prefix(ids)           # runs behind this reply
        return {"target": int(ids.size - 1), "have": int(have)}

    def _h_export_range(self, p, arrays):
        """One stream chunk: blocks from ``start_block`` onward. Waits
        server-side (bounded by ``wait_s``) for at least one new block so
        the client polls the network, not the prefill — a slow chunk
        costs one parked RPC, not a spin."""
        eng = self._engine(p["idx"])
        ids = np.asarray(arrays["prompt"], np.int32).reshape(-1)
        start = int(p.get("start_block", 0))
        max_blocks = p.get("max_blocks")
        deadline = time.monotonic() + float(p.get("wait_s", 1.0))
        while True:
            exp = eng.export_kv_range(ids, start, max_blocks=max_blocks)
            if exp["n_blocks"] > 0 or exp["done"] \
                    or time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        meta = {k: exp[k] for k in ("matched_len", "start_block",
                                    "n_blocks", "block_size", "done",
                                    "covered_tokens")}
        if exp["n_blocks"] <= 0:
            return meta
        FLEET_KV_EXPORTS.add(1)
        return meta, {"kb": exp["kb"], "vb": exp["vb"]}

    def _h_import_chunk(self, p, arrays):
        """Splice one streamed chunk; the returned ``have`` is the ack
        high-water mark the sender resumes from."""
        eng = self._engine(p["idx"])
        have = eng.import_kv_chunk(arrays["prompt"], arrays["kb"],
                                   arrays["vb"], int(p["start_block"]),
                                   int(p["n_tokens"]))
        if have > 0:
            FLEET_KV_IMPORTS.add(1)
        return {"have": int(have)}

    def _h_collect_flight(self, p, arrays):
        """Ship this host's FlightRecorder ring + gauge snapshot to the
        collecting router (fleet-wide post-mortem, ISSUE 20). Unarmed
        hosts answer honestly instead of erroring — a gap in the merged
        timeline, never a hang."""
        rec = get_flight_recorder()
        if rec is None:
            return {"armed": False, "host": self.host, "pid": os.getpid()}
        rec.note_gauges()
        return {"armed": True, "host": self.host, "pid": rec.pid,
                "events": rec.events(), "gauges": stat_snapshot()}

    def _h_ensure_replicas(self, p, arrays):
        """Pre-warm path: grow this host to ``n`` replicas (never
        shrinks — drain-shrink stays a router/supervisor decision)."""
        n = int(p["n"])
        with self._lock:
            have = len(self._engines)
        for _ in range(max(0, n - have)):
            self._spawn_engine()
        self.announce()
        return {"replicas": self._describe()}

    def _h_evacuate(self, p, arrays):
        self._engine(p["idx"]).evacuate()
        return {"ok": True}

    def _h_fail_replica(self, p, arrays):
        self._engine(p["idx"]).fail_at_tick(int(p.get("ticks", 1)))
        return {"ok": True}

    def _h_shutdown_replica(self, p, arrays):
        self._engine(p["idx"]).shutdown(drain=bool(p.get("drain", True)),
                                        timeout=p.get("timeout", 30.0))
        return {"ok": True}

    # -- lifecycle -----------------------------------------------------------
    def close(self, abrupt: bool = False) -> None:
        """Stop serving. ``abrupt=True`` is the host-loss simulation: no
        deregistration, no engine drain — the record just goes stale and
        open sockets die, exactly what a crashed host looks like."""
        self._closed_event.set()
        self._server.close()
        with self._lock:
            engines = list(self._engines)
        if not abrupt:
            for e in engines:
                try:
                    e.shutdown(drain=False, timeout=30)
                except RuntimeError:
                    pass
            try:
                self.registry.retire(self.host)
            except OSError:
                pass
        self._hb.join(timeout=2.0)


# ===========================================================================
# remote replica proxy (client side)
# ===========================================================================
class _RemoteCfg:
    """Just enough of a model config for the router's validation and the
    frontend's metadata endpoints."""

    def __init__(self, vocab_size: int):
        self.vocab_size = int(vocab_size)


class RemoteReplica:
    """Engine-protocol proxy for one replica on another host.

    Submit mirrors the stream locally: tokens arrive through a
    per-request pump thread long-polling the host, pushed into a local
    :class:`GenerationRequest` via the same ``_push``/``_finish`` calls
    the in-process scheduler makes — so ``stream()``/``result()``/SSE
    and the router failover hook behave identically. A transport death
    fails every open mirror with :class:`RemoteReplicaError`, which the
    failover hook turns into adoption by a survivor (token-identical
    replay — rid and seed ride along).
    """

    def __init__(self, client: RpcClient, idx: int, info: dict, host: str,
                 role: str = "mixed", poll_s: float = 1.0,
                 health_ttl: float = 0.2):
        self._client = client
        self.idx = int(idx)
        self.host = str(host)
        self.role = str(role)
        self.poll_s = float(poll_s)
        self.health_ttl = float(health_ttl)
        self.block_size = int(info["block_size"])
        self.prefill_chunk = int(info["prefill_chunk"])
        self.n_slots = int(info["n_slots"])
        self.max_len = int(info["max_len"])
        self.cfg = _RemoteCfg(info["vocab_size"])
        # truthy when the remote engine caches prefixes: arms the
        # router's affinity map exactly like a local radix tree would
        self._prefix = True if info.get("prefix") else None
        # a STATELESS remote tokenizer reconstructs locally, so the
        # router/frontend text surface works over a fleet; stateful
        # tokenizers stay None (text encodes nowhere — ids only)
        if info.get("tokenizer") == "ByteTokenizer":
            from .tokenizer import ByteTokenizer

            self.tokenizer = ByteTokenizer()
        else:
            self.tokenizer = None
        self.overload = None
        self.replica_id = None             # router-assigned
        self.failover = None               # router-installed
        self._lock = threading.Lock()      # guards _open/_lost/health cache
        self._open: Dict[int, GenerationRequest] = {}
        self._lost = False
        self._health_cache: Optional[dict] = None
        self._health_t = 0.0
        self._rid = 0                      # protocol compat (rids live
        self._cv = threading.Condition()   # on the remote engine)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt=None, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_id=None, deadline_s=None, block: bool = True,
               timeout=None, text=None, constraint=None, trace=None):
        if text is not None:
            raise ValueError("RemoteReplica takes token ids — the router "
                             "encodes text before placement")
        if constraint is not None:
            raise ValueError("constrained decoding does not cross the "
                             "RPC boundary")
        ids = np.asarray(prompt, np.int32).reshape(-1)
        req = GenerationRequest(
            ids, max_new_tokens, temperature, top_k, top_p, eos_id,
            None if deadline_s is None else time.monotonic() + deadline_s)
        req.trace = trace
        req._tokenizer = self.tokenizer    # arms text()/stream_text()
        params = {"idx": self.idx, "max_new_tokens": int(max_new_tokens),
                  "temperature": float(temperature), "top_k": int(top_k),
                  "top_p": float(top_p), "eos_id": eos_id,
                  "deadline_s": deadline_s, "block": bool(block),
                  "timeout": timeout}
        rpc_budget = self._client.timeout + (timeout or 0.0)
        try:
            res, _ = self._client.call("submit", params, {"prompt": ids},
                                       timeout=rpc_budget)
        except RpcRemoteError as e:
            if e.etype == "QueueFull":
                raise QueueFull(str(e)) from e
            raise
        except RpcError as e:
            # transport death mid-submit: this replica is unroutable
            # until proven otherwise — mark it lost so open streams
            # reroute and the router re-places the submit elsewhere
            self._mark_lost(e)
            raise
        req.rid = int(res["rid"])
        req._failover = self.failover
        req._t_submit = time.monotonic()
        self._start_pump(int(res["hid"]), req)
        return req

    def adopt_request(self, req: GenerationRequest) -> None:
        """Failover adoption over RPC: the remote engine replays
        ``prompt + tokens[:-1]`` under the request's original rid (the
        preemption-resume contract), and the SAME local mirror keeps
        accumulating — the user's handle never changes."""
        deadline_s = None if req.deadline is None \
            else max(0.0, req.deadline - time.monotonic())
        params = {"idx": self.idx, "rid": int(req.rid),
                  "max_new_tokens": int(req.max_new_tokens),
                  "temperature": float(req.temperature),
                  "top_k": int(req.top_k), "top_p": float(req.top_p),
                  "eos_id": req.eos_id, "deadline_s": deadline_s,
                  "tokens": [int(t) for t in req.tokens]}
        res, _ = self._client.call("adopt", params, {"prompt": req.prompt})
        req._failover = self.failover
        req._t_submit = time.monotonic()
        self._start_pump(int(res["hid"]), req)

    def generate(self, prompt=None, **kw):
        return self.submit(prompt, **kw).result()

    # -- the stream pump -----------------------------------------------------
    def _start_pump(self, hid: int, req: GenerationRequest) -> None:
        with self._lock:
            if self._lost:
                raise RuntimeError(f"replica on host {self.host} is lost")
            self._open[hid] = req
        threading.Thread(target=self._pump, args=(hid, req),
                         name="fleet-pump", daemon=True).start()

    def _pump(self, hid: int, req: GenerationRequest) -> None:
        cursor = len(req.tokens)
        cancel_sent = False
        while True:
            with self._lock:
                if hid not in self._open:
                    return                 # host-loss path owns this stream
            if req._cancelled and not cancel_sent:
                try:
                    self._client.call("cancel", {"hid": hid},
                                      timeout=self.poll_s)
                except RpcError:
                    pass
                cancel_sent = True
            try:
                res, _ = self._client.call(
                    "wait", {"hid": hid, "cursor": cursor,
                             "timeout": self.poll_s},
                    timeout=self.poll_s + self._client.timeout)
            except RpcError as e:
                self._mark_lost(e)
                return
            except RpcRemoteError as e:
                self._finish_owned(hid, req, ERROR, RemoteReplicaError(
                    f"remote wait failed: {e}"))
                return
            fresh = res.get("tokens") or []
            for t in fresh:
                req._push(int(t))
            cursor += len(fresh)
            if res.get("done"):
                err_s = res.get("error")
                self._finish_owned(
                    hid, req, res.get("finish_reason") or ERROR,
                    RemoteReplicaError(err_s) if err_s else None)
                return

    def _finish_owned(self, hid: int, req: GenerationRequest, reason: str,
                      err: Optional[BaseException]) -> None:
        with self._lock:
            owned = self._open.pop(hid, None) is not None
        if owned:
            req._finish(reason, err)

    def _mark_lost(self, err: Optional[BaseException] = None) -> int:
        """Transport death / registry host-loss: fail every open mirror
        (each ``error`` finish offers the stream to the router failover
        hook first — adoption, not loss). Idempotent."""
        with self._lock:
            if self._lost:
                return 0
            self._lost = True
            open_reqs = list(self._open.items())
            self._open.clear()
        cause = err if err is not None else RemoteReplicaError(
            f"host {self.host} lost")
        for _, req in open_reqs:
            FLEET_REROUTES.add(1)
            req._finish(ERROR, cause)
        return len(open_reqs)

    # -- health surface ------------------------------------------------------
    def _health(self) -> Optional[dict]:
        now = time.monotonic()
        with self._lock:
            if self._lost:
                return None
            cache, t = self._health_cache, self._health_t
        if cache is not None and now - t < self.health_ttl:
            return cache
        try:
            res, _ = self._client.call("health", {"idx": self.idx},
                                       timeout=self.health_ttl + 2.0)
        except (RpcError, RpcRemoteError) as e:
            self._mark_lost(e)
            return None
        with self._lock:
            self._health_cache, self._health_t = res, time.monotonic()
        return res

    @property
    def alive(self) -> bool:
        h = self._health()
        return bool(h and h.get("alive"))

    @property
    def busy(self) -> bool:
        h = self._health()
        return bool(h and h.get("busy"))

    def tick_age(self) -> float:
        h = self._health()
        return float(h["tick_age_s"]) if h else float("inf")

    def pool_headroom(self) -> float:
        h = self._health()
        return float(h["pool_headroom"]) if h else 0.0

    @property
    def queue_depth(self) -> int:
        h = self._health()
        return int(h["queue_depth"]) if h else 0

    @property
    def occupancy(self) -> int:
        h = self._health()
        return int(h["occupancy"]) if h else 0

    def heartbeat_age(self) -> float:
        """Seconds since this proxy last heard from its host — the
        fleet-membership staleness the frontend's ``checks.fleet``
        reports."""
        with self._lock:
            t = self._health_t
        return float("inf") if t == 0.0 else time.monotonic() - t

    # -- lifecycle / kv streaming -------------------------------------------
    def warm_prefix(self, prompt) -> GenerationRequest:
        ids = np.asarray(prompt, np.int32).reshape(-1)
        req = GenerationRequest(ids, 1, 0.0, 0, 1.0, None, None)
        try:
            self._client.call("warm", {"idx": self.idx}, {"prompt": ids},
                              timeout=self._client.timeout + 120.0)
            req.finish_reason = LENGTH
        except (RpcError, RpcRemoteError) as e:
            req.finish_reason = ERROR
            req.error = e
        return req

    def export_kv_prefix(self, tokens, timeout=None):
        ids = np.asarray(tokens, np.int32).reshape(-1)
        res, arrs = self._client.call(
            "prefill_export", {"idx": self.idx, "timeout": timeout},
            {"prompt": ids}, timeout=self._client.timeout + 120.0)
        if not res or int(res.get("matched_len", 0)) <= 0:
            return None
        return {"matched_len": int(res["matched_len"]),
                "block_size": int(res["block_size"]),
                "dtype": res.get("dtype"), "shape": list(arrs["kb"].shape),
                "kb": arrs["kb"], "vb": arrs["vb"]}

    def import_kv_prefix(self, tokens, kb, vb, matched_len: int,
                         timeout=None) -> int:
        ids = np.asarray(tokens, np.int32).reshape(-1)
        res, _ = self._client.call(
            "import_kv", {"idx": self.idx, "matched_len": int(matched_len)},
            {"prompt": ids, "kb": np.asarray(kb), "vb": np.asarray(vb)},
            timeout=self._client.timeout + 60.0)
        return int(res.get("cached", 0))

    def prefill_start(self, tokens, timeout=None) -> dict:
        """Start a non-blocking remote radix warm for chunk streaming;
        returns ``{"target", "have"}``."""
        ids = np.asarray(tokens, np.int32).reshape(-1)
        res, _ = self._client.call(
            "prefill_start", {"idx": self.idx, "timeout": timeout},
            {"prompt": ids}, timeout=self._client.timeout + 30.0)
        return res

    def export_kv_range(self, tokens, start_block: int, max_blocks=None,
                        wait_s: float = 1.0, timeout=None) -> dict:
        ids = np.asarray(tokens, np.int32).reshape(-1)
        res, arrs = self._client.call(
            "export_range", {"idx": self.idx,
                             "start_block": int(start_block),
                             "max_blocks": max_blocks,
                             "wait_s": float(wait_s)},
            {"prompt": ids},
            timeout=(timeout or self._client.timeout) + float(wait_s))
        out = dict(res)
        if arrs:
            out["kb"], out["vb"] = arrs["kb"], arrs["vb"]
        return out

    def import_kv_chunk(self, tokens, kb, vb, start_block: int,
                        n_tokens: int, timeout=None) -> int:
        """Chunk splice with blob crc armed — a corrupt-in-flight KV
        chunk fails the call instead of caching wrong rows."""
        ids = np.asarray(tokens, np.int32).reshape(-1)
        res, _ = self._client.call(
            "import_chunk", {"idx": self.idx,
                             "start_block": int(start_block),
                             "n_tokens": int(n_tokens)},
            {"prompt": ids, "kb": np.asarray(kb), "vb": np.asarray(vb)},
            timeout=(timeout or self._client.timeout) + 60.0, crc=True)
        return int(res.get("have", 0))

    def collect_flight(self, timeout: float = 2.0) -> dict:
        res, _ = self._client.call("collect_flight", {}, timeout=timeout)
        return res

    def evacuate(self) -> None:
        try:
            self._client.call("evacuate", {"idx": self.idx})
        except (RpcError, RpcRemoteError) as e:
            self._mark_lost(e)

    def fail_at_tick(self, ticks_ahead: int = 1) -> None:
        self._client.call("fail_replica", {"idx": self.idx,
                                           "ticks": int(ticks_ahead)})

    def shutdown(self, drain: bool = True, timeout=None) -> None:
        """Shut the REMOTE replica down (the fleet owner closing its
        router tears the fleet down), then detach the proxy."""
        try:
            self._client.call("shutdown_replica",
                              {"idx": self.idx, "drain": bool(drain),
                               "timeout": timeout},
                              timeout=(timeout or 30.0)
                              + self._client.timeout)
        except (RpcError, RpcRemoteError):
            pass
        self._mark_lost(RemoteReplicaError(
            f"replica {self.replica_id} on {self.host} shut down"))

    def __repr__(self):
        return (f"RemoteReplica(host={self.host!r}, idx={self.idx}, "
                f"role={self.role!r}, lost={self._lost})")


# ===========================================================================
# arrival forecasting + fleet scheduling
# ===========================================================================
class ArrivalRateForecaster:
    """Measured request arrival rate. Every fleet submission lands one
    inter-arrival gap in ``fleet_arrival_gap_ms`` (the histogram the
    trace/bench reports read) and one timestamp in a sliding window;
    :meth:`rps` is the windowed rate — the pre-warm signal that replaces
    react-to-brownout scaling."""

    def __init__(self, window_s: float = 5.0, max_samples: int = 512):
        self.window_s = float(window_s)
        self._lock = threading.Lock()      # guards _times
        self._times: collections.deque = collections.deque(
            maxlen=int(max_samples))

    def note_arrival(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._times:
                FLEET_ARRIVAL_GAP_MS.observe((now - self._times[-1]) * 1e3)
            self._times.append(now)

    def rps(self) -> float:
        now = time.monotonic()
        with self._lock:
            xs = [t for t in self._times if now - t <= self.window_s]
        if len(xs) < 2:
            return 0.0
        return (len(xs) - 1) / max(1e-6, xs[-1] - xs[0])


class FleetScheduler:
    """Role assignment, per-phase pool sizing, and predictive pre-warm.

    - :meth:`plan_roles` — with one host everything is ``mixed``; with
      more, the first (sorted) host runs prefill and the rest decode.
    - :meth:`pool_plan` — prefill pools trade slots for blocks (few
      concurrent prompts, many block-rows in flight) and take the
      largest chunk; decode pools keep the slots.
    - the pre-warm loop — every ``poll_s``, compare the forecast rps
      against ``rps_per_replica`` x current healthy decode replicas and
      ask decode hosts for more BEFORE the brownout ladder would have
      noticed (``fleet_prewarms`` counts additions).
    """

    def __init__(self, router: "FleetRouter",
                 rps_per_replica: float = 8.0, poll_s: float = 0.5,
                 max_replicas: int = 8):
        self.router = router
        self.rps_per_replica = float(rps_per_replica)
        self.poll_s = float(poll_s)
        self.max_replicas = int(max_replicas)
        router.scheduler = self
        self._stop_event = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-scheduler", daemon=True)
        self._thread.start()

    @staticmethod
    def plan_roles(hosts) -> Dict[str, str]:
        hosts = sorted(str(h) for h in hosts)
        if len(hosts) < 2:
            return {h: "mixed" for h in hosts}
        return {h: ("prefill" if i == 0 else "decode")
                for i, h in enumerate(hosts)}

    @staticmethod
    def pool_plan(role: str, n_slots: int = 4, block_size: int = 16,
                  n_blocks: Optional[int] = None,
                  prefill_chunk: int = 64) -> dict:
        """Engine kwargs for one phase's pool; merge into the host's
        factory kwargs."""
        if role == "prefill":
            return {"n_slots": max(1, n_slots // 2),
                    "block_size": block_size,
                    "n_blocks": n_blocks if n_blocks is None
                    else int(n_blocks * 2),
                    "prefill_chunk": max(prefill_chunk, 4 * block_size)}
        return {"n_slots": n_slots, "block_size": block_size,
                "n_blocks": n_blocks, "prefill_chunk": prefill_chunk}

    def desired_replicas(self, rps: float) -> int:
        return min(self.max_replicas,
                   max(1, math.ceil(rps / self.rps_per_replica)))

    def _loop(self) -> None:
        while not self._stop_event.wait(self.poll_s):
            try:
                self.scan()
            except (RpcError, RpcRemoteError, OSError):
                continue                   # transient: next poll retries

    def scan(self) -> int:
        """One pre-warm decision; returns replicas added."""
        rps = self.router._forecaster.rps()
        need = self.desired_replicas(rps)
        have = len(self.router.healthy_replicas())
        if need <= have:
            return 0
        return self.router.prewarm(need - have)

    def close(self) -> None:
        self._stop_event.set()
        self._thread.join(timeout=2.0)


# ===========================================================================
# fleet router
# ===========================================================================
class FleetRouter(EngineRouter):
    """EngineRouter over a cross-host fleet: registry-driven host-loss
    re-routing, supervisor host offers, predictive pre-warm, and the
    disaggregated prefill->decode KV-streaming submit path. With no
    registry and no prefill pool it IS an EngineRouter — every PR-13/14
    behavior is pinned."""

    def __init__(self, engines, prefill=None, registry: Optional[
            FleetRegistry] = None, host_conns: Optional[dict] = None,
            disagg_min_tokens: Optional[int] = None,
            monitor_poll_s: float = 0.25,
            kv_chunk_blocks: Optional[int] = None, **kw):
        super().__init__(engines, **kw)
        self._prefill_pool: List[RemoteReplica] = list(prefill or [])
        self.registry = registry
        # host -> (client, record): connections the pre-warm path grows
        # replicas through (and shutdown closes)
        self._host_conns: Dict[str, tuple] = dict(host_conns or {})
        self._forecaster = ArrivalRateForecaster()
        self.scheduler: Optional[FleetScheduler] = None
        if disagg_min_tokens is None and self._prefill_pool:
            disagg_min_tokens = 2 * self._prefill_pool[0].block_size
        self._disagg_min = disagg_min_tokens
        # cap blocks per streamed chunk (None = all available): smaller
        # chunks start the decode splice sooner and bound per-frame size
        self.kv_chunk_blocks = kv_chunk_blocks
        self.monitor_poll_s = float(monitor_poll_s)
        self._fleet_lock = threading.Lock()  # guards _hosts_known/_lost
        self._hosts_known: set = set()
        self._lost_hosts: set = set()
        self._members_sig = None           # last fleet.members span payload
        # satellite 2: "registry unreachable" is NOT "hosts dead" — track
        # the partition window so /readyz can report unknowable honestly
        self._registry_down_t = 0.0
        self._storm_latched = False        # breaker-storm collect episode
        self._collect_seq = 0
        self._collect_last_t = 0.0
        self.last_stream_stats: Optional[dict] = None
        self._monitor_stop = threading.Event()
        self._monitor = None
        if registry is not None:
            self._monitor = threading.Thread(target=self._fleet_monitor,
                                             name="fleet-monitor",
                                             daemon=True)
            self._monitor.start()

    # -- membership surface (satellite 2 lives on EngineRouter; this
    # -- override adds the prefill pool, which takes no decode traffic)
    def fleet_members(self) -> Dict:
        out = super().fleet_members()
        for j, pf in enumerate(self._prefill_pool):
            out[f"prefill/{j}"] = {
                "host": pf.host, "role": pf.role,
                "heartbeat_age_s": round(pf.heartbeat_age(), 3)}
        with self._fleet_lock:
            down_t = self._registry_down_t
            lost = set(self._lost_hosts)
        # satellite 2: per-member verdicts distinguish "host dead"
        # (heartbeat stale while the registry answers) from "registry
        # unreachable" (partition — nothing about hosts is knowable, so
        # say that rather than marking the fleet down)
        for m in out.values():
            host = m.get("host")
            if host is None:
                m["status"] = "ok"         # in-process replica
            elif down_t:
                m["status"] = "unknowable"
            elif host in lost:
                m["status"] = "dead"
            else:
                m["status"] = "ok"
        out["registry"] = {
            "reachable": down_t == 0.0,
            "unreachable_for_s": 0.0 if down_t == 0.0
            else round(time.monotonic() - down_t, 3)}
        return out

    # -- host-loss monitor ---------------------------------------------------
    def _fleet_monitor(self) -> None:
        while not self._monitor_stop.wait(self.monitor_poll_s):
            self.fleet_scan()
            self._check_breaker_storm()

    def _check_breaker_storm(self) -> None:
        """Half the fleet's breakers open at once is a NETWORK incident,
        not a host incident — pull the black boxes while they're hot.
        Episode-latched: one collection per storm, re-armed only after
        the breakers recover."""
        conns = self._host_conns
        if not conns:
            return
        n_open = sum(1 for _, (c, _r) in conns.items()
                     if c.breaker is not None
                     and c.breaker.state == BREAKER_OPEN)
        storm = n_open >= max(1, (len(conns) + 1) // 2)
        if storm and not self._storm_latched:
            self._storm_latched = True
            self.collect_flight_async(f"breaker_storm_{n_open}open")
        elif not storm:
            self._storm_latched = False

    def fleet_scan(self) -> None:
        """One registry scan: detect lost/returned hosts and act."""
        try:
            alive = self.registry.alive()
        except OSError:
            with self._fleet_lock:         # partition: no blind verdicts,
                if self._registry_down_t == 0.0:   # but note the window
                    self._registry_down_t = time.monotonic()
            return
        with self._fleet_lock:
            self._registry_down_t = 0.0
        members = {h: {"role": r.get("role", "mixed"),
                       "replicas": int(r.get("replicas", 0))}
                   for h, r in sorted(alive.items())}
        sig = tuple(sorted((h, m["role"], m["replicas"])
                           for h, m in members.items()))
        with self._fleet_lock:
            self._hosts_known |= set(alive)
            newly_lost = self._hosts_known - set(alive) - self._lost_hosts
            returned = set(alive) & self._lost_hosts
            self._lost_hosts |= newly_lost
            self._lost_hosts -= returned
            changed = sig != self._members_sig
            self._members_sig = sig
        FLEET_HOSTS.set(len(alive))
        FLEET_REPLICAS.set(self.n_replicas)
        if changed and recording():
            # membership snapshot for tools/trace_report.py's fleet
            # section: one row per registered host, on every change
            emit_complete("fleet.members", time.perf_counter(), 0.0,
                          cat="serving", args={"hosts": members})
        for host in sorted(newly_lost):
            self._host_lost(host)
        for host in sorted(returned):
            self._host_returned(host)

    def _proxies_of(self, host: str) -> List[RemoteReplica]:
        out = [e for e in self.engines
               if isinstance(e, RemoteReplica) and e.host == host]
        out += [p for p in self._prefill_pool if p.host == host]
        return out

    def _host_lost(self, host: str) -> None:
        rerouted = 0
        for proxy in self._proxies_of(host):
            rerouted += proxy._mark_lost(RemoteReplicaError(
                f"host {host} lost (heartbeat stale)"))
        if recording():
            emit_complete("fleet.host_lost", time.perf_counter(), 0.0,
                          cat="serving",
                          args={"host": host, "rerouted": rerouted})
        # losing a host is a fleet incident: pull every survivor's black
        # box while the evidence is still in the rings (never blocks the
        # monitor — collection runs on its own thread)
        self.collect_flight_async(f"host_lost_{host}")

    def _host_returned(self, host: str) -> None:
        """A host the monitor declared lost is heartbeating again: offer
        it to the supervisor so a quarantined replica id is retried on
        the returned host's clean ladder instead of serving out the dead
        host's sentence (satellite 3)."""
        sup = self.supervisor
        if sup is None or not hasattr(sup, "note_host_offer"):
            return
        for rid, st in sup.snapshot().get("replicas", {}).items():
            if st.get("state") in ("pending", "quarantined"):
                sup.note_host_offer(int(rid), host)

    # -- predictive pre-warm -------------------------------------------------
    def prewarm(self, n: int) -> int:
        """Grow the decode pool by ``n`` replicas across connected
        decode/mixed hosts; returns how many were added."""
        added = 0
        for host, (client, record) in sorted(self._host_conns.items()):
            if added >= n or record.get("role") == "prefill":
                continue
            with self._fleet_lock:
                if host in self._lost_hosts:
                    continue
            known = {e.idx for e in self.engines
                     if isinstance(e, RemoteReplica) and e.host == host}
            want = len(known) + min(n - added, 1)
            try:
                res, _ = client.call("ensure_replicas", {"n": want})
            except (RpcError, RpcRemoteError):
                continue
            for info in res["replicas"]:
                if info["idx"] in known:
                    continue
                proxy = RemoteReplica(client, info["idx"], info, host,
                                      role=record.get("role", "mixed"))
                self.add_replica(proxy)
                added += 1
        if added:
            FLEET_PREWARMS.add(added)
            if recording():
                emit_complete("fleet.prewarm", time.perf_counter(), 0.0,
                              cat="serving", args={"added": added})
        return added

    # -- disaggregated submission --------------------------------------------
    def _healthy_prefill(self) -> Optional[RemoteReplica]:
        for pf in self._prefill_pool:
            with pf._lock:
                lost = pf._lost
            if not lost:
                return pf
        return None

    def submit(self, prompt=None, text: Optional[str] = None, **kw):
        if text is not None:
            if prompt is not None:
                raise ValueError("pass prompt OR text, not both")
            if self.tokenizer is None:
                raise ValueError("submit(text=...) needs engines built "
                                 "with a tokenizer")
            prompt = self.tokenizer.encode(text)
            if kw.get("eos_id") is None:
                kw["eos_id"] = self.tokenizer.eos_id
        if prompt is None:
            raise ValueError("provide a prompt (token ids) or text")
        ids = np.asarray(prompt, np.int32).reshape(-1)
        self._forecaster.note_arrival()
        if self._prefill_pool and self._disagg_min is not None \
                and ids.size >= self._disagg_min:
            req = self._submit_disagg(ids, kw)
            if req is not None:
                return req
        # a submit that dies on the wire is re-PLACED on a different
        # healthy replica — never retried on the same one (submit is not
        # idempotent: a frame that died after delivery would
        # double-generate). The failed proxy marks itself lost, so its
        # open streams reroute and placement stops offering it; worst
        # case is one orphaned generation on a partitioned-but-alive
        # host, never a dropped or duplicated stream on ours.
        last: Optional[BaseException] = None
        for _ in range(max(2, len(self._host_conns) + 1)):
            try:
                return super().submit(prompt=ids, **kw)
            except RpcRemoteError:
                raise                  # the handler refused; host is fine
            except RpcError as e:
                last = e
        raise last

    def _fallback(self, reason: str) -> None:
        """Disagg bailed out: count it and leave the reason in the
        trace so the fleet report can rank fallback causes."""
        FLEET_DIRECT_FALLBACKS.add(1)
        if recording():
            emit_complete("fleet.direct", time.perf_counter(), 0.0,
                          cat="serving", args={"reason": reason})

    def _submit_disagg(self, ids: np.ndarray, kw: dict,
                       stream_budget_s: float = 120.0):
        """Prefill on a prefill-role replica, streaming each finished
        chunk's KV blocks into the chosen decode replica WHILE the next
        chunk computes (sequence-numbered by start block; the receiver's
        ack high-water mark drives resume), then submit there — the
        submit hits the freshly-spliced prefix, so decode never runs the
        long prompt's prefill. A prefill host dying MID-stream is not a
        failure: decode keeps the received prefix and its own chunked
        prefill computes only the missing tail (``fleet_kv_resume_tails``)
        — token-identical either way, because everything rides the pinned
        radix-splice guarantee. Only a stream that delivered NOTHING falls
        back to the monolithic path (``fleet_direct_fallbacks``) —
        disaggregation is an optimization, never a correctness
        dependency."""
        pf = self._healthy_prefill()
        target = self.place(ids)
        if pf is None or target is None:
            if self._prefill_pool:
                self._fallback("no_prefill_host" if pf is None
                               else "no_decode_target")
            return None
        eng = self.engine_for(target)
        if getattr(eng, "_prefix", None) is None:
            self._fallback("target_without_prefix_cache")
            return None
        t0 = time.monotonic()
        try:
            start = pf.prefill_start(ids)
        except (RpcError, RpcRemoteError, RuntimeError):
            self._fallback("prefill_start_failed")
            return None
        bs = int(pf.block_size)
        stream_target = int(start["target"])
        deadline = t0 + float(stream_budget_s)
        ack = chunks = nbytes = 0
        first_block_ms = None
        resumed = False
        while ack < stream_target and time.monotonic() < deadline:
            try:
                exp = pf.export_kv_range(ids, start_block=ack // bs,
                                         max_blocks=self.kv_chunk_blocks,
                                         wait_s=1.0)
            except (RpcError, RpcRemoteError):
                # prefill host died mid-transfer: keep what we have —
                # decode's own prefill covers only the missing tail
                resumed = ack > 0
                break
            if exp["n_blocks"] <= 0:
                if exp["done"] and int(exp["matched_len"]) <= ack:
                    break                  # nothing more will ever come
                continue                   # server waited; poll again
            try:
                got = eng.import_kv_chunk(ids, exp["kb"], exp["vb"],
                                          int(exp["start_block"]),
                                          int(exp["covered_tokens"]))
            except (RpcError, RpcRemoteError, RuntimeError, ValueError):
                break                      # decode refused: stop streaming
            chunks += 1
            FLEET_KV_CHUNKS_STREAMED.add(1)
            nbytes += int(exp["kb"].nbytes) + int(exp["vb"].nbytes)
            if first_block_ms is None:
                first_block_ms = (time.monotonic() - t0) * 1e3
            if got <= ack:
                break                      # no progress (pool full): stop
            ack = got
            if exp["done"] and ack >= int(exp["matched_len"]):
                break
        if ack <= 0:
            self._fallback("prefill_stream_failed" if chunks == 0
                           else "decode_import_failed")
            return None
        if resumed:
            FLEET_KV_RESUME_TAILS.add(1)
        dt_ms = (time.monotonic() - t0) * 1e3
        FLEET_KV_TRANSFER_MS.observe(dt_ms)
        FLEET_KV_TRANSFER_BYTES.add(nbytes)
        FLEET_PREFILL_ROUTED.add(1)
        self.last_stream_stats = {
            "first_block_ms": first_block_ms, "total_ms": dt_ms,
            "chunks": chunks, "acked_tokens": int(ack),
            "target_tokens": stream_target, "resumed": resumed}
        if recording():
            emit_complete("fleet.kv_stream", time.perf_counter(),
                          dt_ms / 1e3, cat="serving",
                          args={"bytes": nbytes, "ms": round(dt_ms, 3),
                                "matched": int(ack), "chunks": chunks,
                                "first_block_ms": None
                                if first_block_ms is None
                                else round(first_block_ms, 3),
                                "resumed": resumed,
                                "prefill_host": pf.host,
                                "decode_replica": int(target)})
        try:
            req = eng.submit(prompt=ids, **kw)
        except (RpcError, RuntimeError):
            self._fallback("decode_submit_failed")
            return None
        req._replica = target
        self._affinity_note(ids, target)
        return req

    # -- fleet-wide flight collection (ISSUE 20) -----------------------------
    def collect_flight(self, reason: str, trace_dir: Optional[str] = None,
                       timeout: float = 2.0) -> dict:
        """Pull every reachable host's FlightRecorder ring over RPC into
        flight-format files next to the router's own dump, so
        ``tools/trace_report.py merge_traces`` stitches the incident into
        one fleet timeline. Unreachable hosts become recorded gaps —
        collection is bounded by ``timeout`` per host and never hangs on
        the very failure it is documenting."""
        rec = get_flight_recorder()
        d = trace_dir or (rec.trace_dir if rec is not None else None)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))[:48] or "collect"
        paths, hosts_ok, gaps, unarmed = [], [], [], []
        local = dump_flight(f"fleet_{safe}", trace_dir=d)
        if local:
            paths.append(local)
        with self._fleet_lock:
            self._collect_seq += 1
            seq = self._collect_seq
        for host, (client, _record) in sorted(self._host_conns.items()):
            try:
                res, _ = client.call("collect_flight", {}, timeout=timeout)
            except (RpcError, RpcRemoteError):
                gaps.append(host)
                continue
            if not res.get("armed"):
                unarmed.append(host)
                continue
            hosts_ok.append(host)
            if not d:
                continue
            pid = int(res.get("pid", 0))
            events = list(res.get("events") or ())
            payload = {
                "traceEvents": events + [
                    {"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": f"{host} pid={pid}"}}],
                "displayTimeUnit": "ms",
                "flight": {"reason": f"fleet_{safe}", "host": host,
                           "pid": pid, "seq": seq, "events": len(events),
                           "collected_by": "fleet-router",
                           "gauges": res.get("gauges", {})},
            }
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flight_{host}_{pid}_c{seq:03d}_{safe}.json")
                with open(path, "w") as f:
                    json.dump(payload, f)
                paths.append(path)
            except OSError:
                gaps.append(host)          # disk trouble ≈ lost dump
        FLIGHT_COLLECTS.add(1)
        if recording():
            emit_complete("fleet.collect", time.perf_counter(), 0.0,
                          cat="serving",
                          args={"reason": str(reason),
                                "hosts_ok": hosts_ok, "gaps": gaps,
                                "unarmed": unarmed})
        return {"reason": str(reason), "hosts": hosts_ok, "gaps": gaps,
                "unarmed": unarmed, "paths": paths}

    def collect_flight_async(self, reason: str,
                             min_gap_s: float = 5.0) -> bool:
        """Fire-and-forget :meth:`collect_flight` on a daemon thread —
        the form every trigger that holds a lock (supervisor give-up,
        host-loss monitor) must use. Rate-limited so an incident storm
        produces one collection, not one per symptom."""
        now = time.monotonic()
        with self._fleet_lock:
            if now - self._collect_last_t < float(min_gap_s):
                return False
            self._collect_last_t = now
        threading.Thread(target=lambda: self.collect_flight(reason),
                         name="fleet-collect", daemon=True).start()
        return True

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self, drain: bool = True, timeout=None) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        if self.scheduler is not None:
            self.scheduler.close()
        for pf in self._prefill_pool:
            pf.shutdown(drain=False, timeout=timeout)
        super().shutdown(drain=drain, timeout=timeout)
        for _, (client, _rec) in sorted(self._host_conns.items()):
            client.close()


# ===========================================================================
# discovery
# ===========================================================================
def connect_fleet(store, job: str, min_hosts: int = 1,
                  timeout: float = 30.0, registry_ttl: float = 2.0,
                  rpc_timeout: float = 30.0, poll_s: float = 1.0,
                  client_host: str = "router",
                  retry: Optional[RetryPolicy] = None,
                  breaker_threshold: int = 3,
                  breaker_cooldown_s: float = 2.0,
                  **router_kw) -> FleetRouter:
    """Discover the fleet from the shared store and build a
    :class:`FleetRouter` over it: one RPC connection per host, one
    :class:`RemoteReplica` per (host, replica), prefill-role hosts into
    the KV-streaming pool and everyone else into the routable decode
    set. Blocks until ``min_hosts`` hosts are registered.

    Every host connection is armed with the reliability layer (ISSUE
    20): ``retry`` (default :class:`RetryPolicy` — idempotent-only,
    deterministic backoff; pass ``RetryPolicy(max_attempts=1)`` to
    disable) and a per-peer :class:`CircuitBreaker` (``breaker_threshold``
    consecutive transport errors open it, half-open probe after
    ``breaker_cooldown_s``). ``client_host`` names this endpoint for
    ``net_partition`` fault matching."""
    registry = FleetRegistry(store, job, ttl=registry_ttl)
    deadline = time.monotonic() + timeout
    alive: Dict[str, dict] = {}
    while time.monotonic() < deadline:
        try:
            alive = registry.alive()
        except OSError:
            alive = {}
        if len(alive) >= min_hosts:
            break
        time.sleep(0.05)
    if len(alive) < min_hosts:
        raise TimeoutError(
            f"fleet {job!r}: {len(alive)}/{min_hosts} hosts registered "
            f"after {timeout}s")
    if retry is None:
        retry = RetryPolicy()
    decode, prefill, conns = [], [], {}
    for host, record in sorted(alive.items()):
        client = RpcClient(
            tuple(record["addr"]), timeout=rpc_timeout, retry=retry,
            breaker=CircuitBreaker(threshold=breaker_threshold,
                                   cooldown_s=breaker_cooldown_s,
                                   peer=host),
            peer_host=host, local_host=client_host)
        hello, _ = client.call("hello")
        conns[host] = (client, record)
        role = hello.get("role", record.get("role", "mixed"))
        for info in hello["replicas"]:
            proxy = RemoteReplica(client, info["idx"], info, host,
                                  role=role, poll_s=poll_s)
            (prefill if role == "prefill" else decode).append(proxy)
    if not decode:
        raise RuntimeError(f"fleet {job!r} has no decode-capable host "
                           "(every registered host is prefill-role)")
    return FleetRouter(decode, prefill=prefill, registry=registry,
                       host_conns=conns, **router_kw)
