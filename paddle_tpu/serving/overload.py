"""Brownout degradation ladder for the serving stack (ISSUE 13).

Under sustained overload a serving system has exactly two honest
choices: degrade gracefully or shed loudly. This module implements the
controller that decides WHICH, one rung at a time::

    rung 0  healthy        — nothing changed, bit-identical serving
    rung 1  no_spec        — speculative decoding off (draft ticks cost
                             k+1 verify positions of latency headroom)
    rung 2  small_chunks   — prefill chunks shrink (long prompts yield
                             the scheduler to open streams more often)
    rung 3  capped_tokens  — per-lane max_tokens cap (gold exempt):
                             long generations finish early instead of
                             holding slots through the storm
    rung 4  shed_bronze    — bronze-lane admissions answered 503
    rung 5  shed_silver    — silver too; only gold is admitted

The controller maintains exponentially-weighted moving averages of the
two queue-theory tells — admission QUEUE WAIT (how long work sits before
a slot runs it) and DECODE TICK latency (how slow the slot machinery
itself has become) — each normalized by its budget; ``pressure`` is the
worse of the two. Hysteresis keeps the ladder from flapping: the rung
steps UP only after ``step_up_after`` consecutive observations with
pressure above ``high_water``, and DOWN only after ``step_down_after``
consecutive observations below ``low_water`` (a deliberately lower
mark — recovery must be proven, not glimpsed). Every transition sets the
``brownout_rung`` gauge, counts ``brownout_steps``, and drops a
``serving.brownout`` trace instant that
``tools/trace_report.py overload_report`` turns into a rung timeline.

Consumers:

- :class:`~paddle_tpu.serving.engine.InferenceEngine` (``overload=``)
  feeds ``observe_queue_wait`` at admission and ``observe_tick`` per
  decode tick, and consults ``spec_allowed()`` / ``prefill_chunk()``;
- :class:`~paddle_tpu.serving.frontend.ServingFrontend` feeds WFQ lane
  waits and consults ``sheds(lane)`` (503 + Retry-After) and
  ``cap_max_tokens(lane, n)`` at admission;
- :class:`~paddle_tpu.serving.router.EngineRouter` shares ONE controller
  across every replica, so pressure anywhere brownouts everywhere
  (a half-browned-out pod serves inconsistent latency);
- :class:`~paddle_tpu.serving.lifecycle.ReplicaSupervisor` (ISSUE 14)
  polls ``rung`` as its autoscaling signal: sustained rung >=
  ``scale_up_rung`` grows the replica set, sustained rung 0 with low
  occupancy drains-and-shrinks it — ``rung_held_s()`` (how long the
  ladder has sat at the current rung) rides in ``snapshot()`` so the
  operator view shows whether pressure is a blip or a trend.

With no controller attached (the default everywhere) every compiled
program, schedule decision and sampled token is bit-identical to a build
without this module — the ladder is opt-in, and rung 0 changes nothing
but bookkeeping.

Thread-safety: observations arrive from engine scheduler threads and
the frontend loop thread concurrently; all mutable state is guarded by
one lock. Deadline math is ``time.monotonic`` throughout (GL008).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ..monitor.stats import BROWNOUT_RUNG, BROWNOUT_STEPS
from ..monitor.trace import emit_complete, emit_instant, recording

__all__ = ["OverloadController", "RUNG_NAMES", "RUNG_HEALTHY",
           "RUNG_NO_SPEC", "RUNG_SMALL_CHUNKS", "RUNG_CAPPED_TOKENS",
           "RUNG_SHED_BRONZE", "RUNG_SHED_SILVER"]

RUNG_HEALTHY = 0
RUNG_NO_SPEC = 1
RUNG_SMALL_CHUNKS = 2
RUNG_CAPPED_TOKENS = 3
RUNG_SHED_BRONZE = 4
RUNG_SHED_SILVER = 5

RUNG_NAMES = ("healthy", "no_spec", "small_chunks", "capped_tokens",
              "shed_bronze", "shed_silver")


class OverloadController:
    """EWMA pressure controller stepping the brownout ladder.

    ::

        ctl = OverloadController(queue_wait_budget_ms=200,
                                 tick_budget_ms=100)
        eng = InferenceEngine(cfg, params, overload=ctl)
        fe = ServingFrontend(eng)        # discovers eng.overload

    Knobs: ``queue_wait_budget_ms`` / ``tick_budget_ms`` are the SLO
    normalizers (pressure 1.0 = exactly at budget); ``alpha`` the EWMA
    smoothing weight of a fresh sample; ``high_water`` / ``low_water``
    the asymmetric thresholds; ``step_up_after`` / ``step_down_after``
    the consecutive-observation hysteresis counts; ``chunk_shrink`` the
    divisor applied to prefill chunks at rung >= 2; ``token_cap`` the
    per-request max_tokens ceiling for non-gold lanes at rung >= 3.
    """

    def __init__(self, queue_wait_budget_ms: float = 200.0,
                 tick_budget_ms: float = 100.0, alpha: float = 0.3,
                 high_water: float = 1.0, low_water: float = 0.5,
                 step_up_after: int = 3, step_down_after: int = 8,
                 chunk_shrink: int = 4, token_cap: int = 32):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha={alpha} must be in (0, 1]")
        if low_water >= high_water:
            raise ValueError(f"low_water={low_water} must sit below "
                             f"high_water={high_water} (that gap IS the "
                             "hysteresis band)")
        if chunk_shrink < 1:
            raise ValueError(f"chunk_shrink={chunk_shrink} must be >= 1")
        self.queue_wait_budget_ms = float(queue_wait_budget_ms)
        self.tick_budget_ms = float(tick_budget_ms)
        self.alpha = float(alpha)
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.step_up_after = int(step_up_after)
        self.step_down_after = int(step_down_after)
        self.chunk_shrink = int(chunk_shrink)
        self.token_cap = int(token_cap)
        self._lock = threading.Lock()
        self._rung = RUNG_HEALTHY
        self._rung_since = time.monotonic()   # last transition (dwell time)
        self._q_ewma = 0.0
        self._t_ewma = 0.0
        self._hot = 0           # consecutive observations above high_water
        self._cool = 0          # consecutive observations below low_water
        BROWNOUT_RUNG.set(0)

    # -- observations (engine scheduler thread / frontend loop thread) -------
    def observe_queue_wait(self, ms: float) -> None:
        """One admission's queue wait (engine submit->admit, or the
        front end's WFQ lane wait)."""
        with self._lock:
            self._q_ewma += self.alpha * (float(ms) - self._q_ewma)
            self._maybe_step()

    def observe_tick(self, ms: float) -> None:
        """One decode tick's wall latency."""
        with self._lock:
            self._t_ewma += self.alpha * (float(ms) - self._t_ewma)
            self._maybe_step()

    # -- state ---------------------------------------------------------------
    @property
    def rung(self) -> int:
        return self._rung

    @property
    def rung_name(self) -> str:
        return RUNG_NAMES[self._rung]

    def pressure(self) -> float:
        """Worst normalized EWMA: 1.0 = exactly at budget."""
        with self._lock:
            return self._pressure()

    def rung_held_s(self) -> float:
        """Seconds the ladder has sat at the CURRENT rung — the
        blip-vs-trend signal behind lifecycle autoscaling decisions."""
        with self._lock:
            return time.monotonic() - self._rung_since

    def snapshot(self) -> dict:
        """Readyz/operator view of the controller."""
        with self._lock:
            return {"rung": self._rung, "rung_name": RUNG_NAMES[self._rung],
                    "rung_held_s": round(
                        time.monotonic() - self._rung_since, 3),
                    "pressure": round(self._pressure(), 4),
                    "queue_wait_ewma_ms": round(self._q_ewma, 3),
                    "tick_ewma_ms": round(self._t_ewma, 3)}

    def _pressure(self) -> float:
        return max(self._q_ewma / self.queue_wait_budget_ms,
                   self._t_ewma / self.tick_budget_ms)

    def _maybe_step(self) -> None:
        p = self._pressure()
        if p >= self.high_water:
            self._hot += 1
            self._cool = 0
            if self._hot >= self.step_up_after \
                    and self._rung < RUNG_SHED_SILVER:
                self._set_rung(self._rung + 1, p)
                self._hot = 0
        elif p <= self.low_water:
            self._cool += 1
            self._hot = 0
            if self._cool >= self.step_down_after \
                    and self._rung > RUNG_HEALTHY:
                self._set_rung(self._rung - 1, p)
                self._cool = 0
        else:
            # inside the hysteresis band: hold the rung, reset streaks
            self._hot = 0
            self._cool = 0

    def _set_rung(self, rung: int, pressure: float) -> None:
        # lock held by caller
        prev = self._rung
        self._rung = int(rung)
        self._rung_since = time.monotonic()
        BROWNOUT_RUNG.set(self._rung)
        BROWNOUT_STEPS.add(1)
        if recording():
            emit_instant("serving.brownout", time.perf_counter(),
                         cat="serving")
            # instants carry no args in the writer API — follow with a
            # zero-duration span so the report gets the rung/pressure
            t = time.perf_counter()
            emit_complete("serving.brownout_step", t, 0.0, cat="serving",
                          args={"rung": self._rung,
                                "rung_name": RUNG_NAMES[self._rung],
                                "from": prev,
                                "pressure": round(pressure, 4)})

    def force_rung(self, rung: int) -> None:
        """Operator/test hook: pin the ladder to a rung (the controller
        keeps stepping from there as observations arrive)."""
        if not 0 <= int(rung) <= RUNG_SHED_SILVER:
            raise ValueError(f"rung={rung} outside 0..{RUNG_SHED_SILVER}")
        with self._lock:
            if int(rung) != self._rung:
                self._set_rung(int(rung), self._pressure())
            self._hot = 0
            self._cool = 0

    # -- ladder knobs (consumed by engine/frontend/router) -------------------
    def spec_allowed(self) -> bool:
        """Rung 1: speculative decode is the first thing to go."""
        return self._rung < RUNG_NO_SPEC

    def prefill_chunk(self, base: Optional[int]) -> Optional[int]:
        """Rung 2: shrink prefill chunks by ``chunk_shrink`` (the engine
        re-rounds to its block size, floored at one block)."""
        if base is None or self._rung < RUNG_SMALL_CHUNKS:
            return base
        return max(1, int(base) // self.chunk_shrink)

    def cap_max_tokens(self, lane: str, requested: int) -> int:
        """Rung 3: non-gold lanes get their generations capped."""
        if self._rung < RUNG_CAPPED_TOKENS or lane == "gold":
            return int(requested)
        return min(int(requested), self.token_cap)

    def sheds(self, lane: str) -> bool:
        """Rungs 4/5: admission-time shed verdict for a lane (503 +
        Retry-After at the front end — never a silent drop)."""
        if lane == "bronze":
            return self._rung >= RUNG_SHED_BRONZE
        if lane == "silver":
            return self._rung >= RUNG_SHED_SILVER
        return False        # gold is never shed by the ladder

    def __repr__(self):
        return (f"OverloadController(rung={self._rung}"
                f"/{RUNG_NAMES[self._rung]}, "
                f"pressure={self.pressure():.3f})")
