"""Discrete Fourier transforms (parity surface: reference
python/paddle/fft.py + python/paddle/tensor/fft.py).

All transforms lower to XLA's FFT HLO via jnp.fft; gradients come from
jax.vjp through apply_op like every other op. The Hermitian family
(hfft*/ihfft*) is expressed through the standard identities
``hfft(x) = irfft(conj(x), norm=swap(norm))`` and
``ihfft(x) = conj(rfft(x, norm=swap(norm)))`` — the same construction the
reference's fft_c2r/fft_r2c kernels implement
(/root/reference/python/paddle/tensor/fft.py:1404,1367).
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor, apply_op
from .framework import dtype as dtypes

__all__ = [
    "fft", "fft2", "fftn", "ifft", "ifft2", "ifftn",
    "rfft", "rfft2", "rfftn", "irfft", "irfft2", "irfftn",
    "hfft", "hfft2", "hfftn", "ihfft", "ihfft2", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            "norm should be 'backward', 'ortho' or 'forward', got %r" % (norm,))
    return norm


def _swap_norm(norm):
    """forward<->backward (ortho is self-inverse) — numpy's _swap_direction."""
    return {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]


# impl wrappers are defined ONCE at module level: apply_op's jit cache is
# keyed on (fn, attrs), so a per-call closure would recompile every call.
def _fft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def _ifft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def _rfft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def _irfft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_fft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="fft")


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_ifft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="ifft")


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_rfft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_irfft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="irfft")


def _hfft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(jnp.conj(x), n=n, axis=axis, norm=_swap_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_hfft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="hfft")


def _ihfft_impl(x, n=None, axis=-1, norm="backward"):
    return jnp.conj(jnp.fft.rfft(x, n=n, axis=axis, norm=_swap_norm(norm)))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_ihfft_impl, x, n=n, axis=int(axis), norm=norm,
                    op_name="ihfft")


def _tupled(v):
    if v is None:
        return None
    return tuple(int(i) for i in v)


def _fftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def _ifftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def _rfftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def _irfftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_fftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="fftn")


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_ifftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="ifftn")


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_rfftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_irfftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="irfftn")


def _hfftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(jnp.conj(x), s=s, axes=axes, norm=_swap_norm(norm))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_hfftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="hfftn")


def _ihfftn_impl(x, s=None, axes=None, norm="backward"):
    return jnp.conj(jnp.fft.rfftn(x, s=s, axes=axes, norm=_swap_norm(norm)))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    return apply_op(_ihfftn_impl, x, s=_tupled(s), axes=_tupled(axes),
                    norm=norm, op_name="ihfftn")


def _check_2d_axes(axes):
    axes = _tupled(axes)
    if axes is not None and len(axes) != 2:
        raise ValueError("axes for a 2-D transform must have length 2")
    return axes


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=_check_2d_axes(axes), norm=norm, name=name)


def fftfreq(n, d=1.0, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype else dtypes.default_float_dtype()
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)).astype(dt))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype else dtypes.default_float_dtype()
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)).astype(dt))


def _fftshift_impl(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(int(a) for a in axes)
    elif axes is not None:
        axes = int(axes)
    return apply_op(_fftshift_impl, x, axes=axes, op_name="fftshift")


def _ifftshift_impl(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(int(a) for a in axes)
    elif axes is not None:
        axes = int(axes)
    return apply_op(_ifftshift_impl, x, axes=axes, op_name="ifftshift")
