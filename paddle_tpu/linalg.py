"""paddle.linalg namespace (reference python/paddle/linalg.py).

Thin re-export of the tensor.linalg op set, plus ``cond`` which the
reference exposes only here.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.core import apply_op
from .tensor.linalg import (  # noqa: F401
    cholesky, det, eig, eigh, eigvals, eigvalsh, inv, lstsq, lu,
    matrix_power, matrix_rank, multi_dot, norm, pinv, qr, slogdet, solve,
    svd, triangular_solve, cov, corrcoef,
)

__all__ = [
    "cholesky", "cond", "det", "eig", "eigh", "eigvals", "inv",
    "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv", "qr",
    "slogdet", "solve", "svd",
]


def _cond_impl(x, p=2):
    if p in ("fro", "nuc") or isinstance(p, (int, float)):
        if p == 2 or p == -2:
            s = jnp.linalg.svd(x, compute_uv=False)
            if p == 2:
                return s[..., 0] / s[..., -1]
            return s[..., -1] / s[..., 0]
        return (jnp.linalg.norm(x, ord=p, axis=(-2, -1))
                * jnp.linalg.norm(jnp.linalg.inv(x), ord=p, axis=(-2, -1)))
    raise ValueError("unsupported norm order for cond: %r" % (p,))


def cond(x, p=None, name=None):
    """Condition number w.r.t. matrix norm ``p``
    (reference python/paddle/tensor/linalg.py:549)."""
    if p is None:
        p = 2
    return apply_op(_cond_impl, x, p=p, op_name="cond")
