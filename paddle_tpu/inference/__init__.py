"""Compiled-serve inference API.

Parity: reference AnalysisPredictor
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h:86 —
load model → analysis/optimization passes → compiled program → zero-copy
run) and the Config/create_predictor API (paddle_inference_api.h).

TPU-native: the exported artifact (static/export.py) already IS optimized
compiler IR (StableHLO), so the analysis-pass pipeline collapses into
PJRT compilation: deserialize once, AOT-compile per input-shape signature
(symbolic-dim exports compile once for all batch sizes), keep weights
device-resident, and feed/fetch through dlpack-free jax device arrays —
the functional analog of the reference's zero-copy tensors.

No model-building Python is imported: a serving process needs only
``paddle_tpu.inference`` and numpy.

Scope: this is the ONE-SHOT compiled-program surface (classification,
embedding, single forward passes). For autoregressive generation under
concurrent traffic — KV-cache decode, continuous batching, streaming —
use :mod:`paddle_tpu.serving` (InferenceEngine), which serves many
requests through one jitted decode step instead of one program run per
call.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """AnalysisConfig analog: points at the exported artifact."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.path_prefix = prog_file
        self._device = None

    # reference-API knobs that are automatic under PJRT: accepted, no-ops
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = ("gpu", device_id)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, enable=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class _IOTensor:
    """Zero-copy-style handle (reference ZeroCopyTensor): holds the array
    slot for a named input/output."""

    def __init__(self, owner, name):
        self._owner = owner
        self._name = name

    def copy_from_cpu(self, arr):
        self._owner._inputs[self._name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the array in copy_from_cpu

    def copy_to_cpu(self):
        return np.asarray(self._owner._outputs[self._name])


class Predictor:
    """Load an exported inference artifact and serve it.

    ``Predictor(path).run([inputs...]) -> [outputs...]`` — AOT-compiles on
    first call per shape signature; symbolic-dim exports compile once.
    """

    def __init__(self, path_or_config):
        from ..static.export import (ExportedInference, is_stablehlo_model,
                                     read_artifacts)

        path = (path_or_config.path_prefix
                if isinstance(path_or_config, Config) else path_or_config)
        if path.endswith(".pdmodel"):
            path = path[:-len(".pdmodel")]
        if not is_stablehlo_model(path):
            raise ValueError(
                f"{path}.pdmodel is not a versioned StableHLO export — "
                "re-save with paddle_tpu.static.save_inference_model")
        data, state, meta = read_artifacts(path)
        self._exported = ExportedInference(data, state, meta)
        self.meta = meta
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    # -- reference-style named IO -------------------------------------------
    def get_input_names(self) -> List[str]:
        return self._exported.feed_names

    def get_output_names(self) -> List[str]:
        return [f"fetch_{i}" for i in range(self.meta["fetch_count"])]

    def get_input_handle(self, name) -> _IOTensor:
        # validate at handle creation (a bad name used to surface only as
        # a cryptic KeyError inside copy_to_cpu, long after the mistake)
        names = self.get_input_names()
        if name not in names:
            raise ValueError(
                f"unknown input name {name!r}; this model's inputs are "
                f"{names} (get_input_names())")
        return _IOTensor(self, name)

    def get_output_handle(self, name) -> _IOTensor:
        names = self.get_output_names()
        if name not in names:
            raise ValueError(
                f"unknown output name {name!r}; this model's outputs are "
                f"{names} (get_output_names())")
        return _IOTensor(self, name)

    # -- execution ------------------------------------------------------------
    def run(self, inputs: Optional[Sequence] = None):
        """inputs: list aligned with get_input_names(), or None to use
        values staged via input handles. Returns list of np.ndarray."""
        names = self._exported.feed_names
        if inputs is not None:
            feed = dict(zip(names, inputs))
        else:
            feed = dict(self._inputs)
        vals = self._exported.run(feed)
        out = [np.asarray(v) for v in vals]
        self._outputs = {f"fetch_{i}": v for i, v in enumerate(out)}
        return out


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
