"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capability surface of PaddlePaddle (reference:
/root/reference, ~v2.1) on JAX/XLA/Pallas. The public API mirrors
``paddle.*`` so reference users can switch with an import rename:

    import paddle_tpu as paddle

Architecture (vs the reference):
- eager mode = Tensor wrapper + vjp tape (framework/core.py) instead of
  Tracer/BasicEngine C++ runtime;
- compiled mode = jax.jit/pjit traces instead of ProgramDesc+Executor;
- kernels = XLA + Pallas instead of the 356k-LoC operator library;
- distribution = jax.sharding Mesh + XLA collectives instead of NCCL rings.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .core import native as _native_flags

_native_flags.apply_shardy_flag()  # FLAGS_shardy: sdy partitioner dialect

from .framework import dtype as _dtype_mod
from .framework.dtype import (
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
)
from .framework.core import (
    Tensor,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
)
from .framework.random import seed, get_rng_state, set_rng_state
from .framework.dtype import dtype  # noqa: F401  (paddle.dtype)
from .batch import batch  # noqa: F401
from .framework.core import grad  # noqa: F401  (paddle.grad)

from .tensor import *  # noqa: F401,F403 — op namespace at top level (paddle.add, ...)
from .tensor import einsum  # noqa: F401

from .device import (
    set_device, get_device, device_count, CPUPlace, CUDAPlace, TPUPlace,
    XPUPlace, NPUPlace, CUDAPinnedPlace, is_compiled_with_cuda,
    is_compiled_with_xpu, is_compiled_with_npu, is_compiled_with_tpu,
)


def set_flags(flags):
    """paddle.set_flags parity (reference pybind global_value_getter_setter):
    dict of FLAGS_* names → values, stored in the native registry."""
    from .core import set_flag

    for k, v in dict(flags).items():
        set_flag(k, v)


def get_flags(flags):
    from .core import get_flag

    if isinstance(flags, str):
        flags = [flags]
    return {k: get_flag(k) for k in flags}

from . import tensor  # noqa: F401
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import inference  # noqa: F401
from . import distributed  # noqa: F401
from . import device  # noqa: F401
from . import utils  # noqa: F401
from . import ops  # noqa: F401
from . import distribution  # noqa: F401
from . import onnx  # noqa: F401
from . import fft  # noqa: F401
from . import fluid  # noqa: F401
# NOT `from . import linalg`: the tensor star-import above already bound
# `linalg` to tensor.linalg, which would stop the submodule import; the
# absolute import always loads paddle_tpu/linalg.py and rebinds the attr.
import paddle_tpu.linalg  # noqa: F401,E402
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import resilience  # noqa: F401
from . import incubate  # noqa: F401
from . import quantization  # noqa: F401

from .hapi.model import Model  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .jit import to_static  # noqa: F401

# dygraph-parity helpers
from .nn import DataParallel  # noqa: F401


def __getattr__(name):
    # paddle_tpu.serving is LAZY (PEP 562): it imports the model code
    # (models.gpt prefill/decode variants), and a Predictor-only serving
    # process must stay model-code-free (test_inference pins that a fresh
    # process importing paddle_tpu.inference never loads paddle_tpu.models)
    if name == "serving":
        import importlib

        return importlib.import_module(".serving", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def in_dynamic_mode() -> bool:
    from .static import _static_mode

    return not _static_mode[0]


def enable_static():
    from .static import _static_mode

    _static_mode[0] = True


def disable_static():
    from .static import _static_mode

    _static_mode[0] = False


def is_grad_enabled_():
    return is_grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr print options (reference tensor/to_string.py
    set_printoptions); Tensor repr renders through numpy, so this delegates
    to np.set_printoptions."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op for parity: the reference installs C++ signal handlers for
    crash stacks (paddle/fluid/platform/init.cc); this runtime leaves
    Python's handlers in place, so there is nothing to disable."""


def get_cuda_rng_state():
    """Device RNG state (name kept for parity; state is the jax PRNG key)."""
    return [get_rng_state()]


def set_cuda_rng_state(state_list):
    if isinstance(state_list, (list, tuple)):
        state_list = state_list[0]
    set_rng_state(state_list)
