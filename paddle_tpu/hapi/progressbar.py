"""Progress bar (reference python/paddle/hapi/progressbar.py)."""
from __future__ import annotations

import sys
import time

import numpy as np


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._values = {}
        self._seen = 0
        # monotonic: elapsed/ms-per-step math must not go negative or
        # jump on an NTP step (graftlint GL008)
        self._start = time.monotonic()

    def update(self, current_num, values=None):
        now = time.monotonic()
        values = values or []
        for k, v in values:
            self._values[k] = v
        if self._verbose != 1:
            return
        msg = f"step {current_num}"
        if self._num:
            msg += f"/{self._num}"
        for k, v in self._values.items():
            if isinstance(v, (float, np.floating)):
                msg += f" - {k}: {v:.4f}"
            elif isinstance(v, (list, np.ndarray)):
                msg += f" - {k}: " + " ".join(f"{x:.4f}" for x in np.ravel(v)[:3])
            else:
                msg += f" - {k}: {v}"
        elapsed = now - self._start
        msg += f" - {1000*elapsed/max(current_num,1):.0f}ms/step"
        self.file.write("\r" + msg)
        if self._num and current_num >= self._num:
            self.file.write("\n")
        self.file.flush()
