"""Model summary (reference python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    rows = []
    total_params = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for p in layer._parameters.values():
            if p is not None:
                n_params += p.size
                total_params += p.size
                if getattr(p, "trainable", True):
                    trainable += p.size
        if n_params:
            rows.append((name or type(layer).__name__, type(layer).__name__, n_params))
    width = max([len(r[0]) for r in rows], default=20) + 2
    print(f"{'Layer':<{width}}{'Type':<24}{'Params':>12}")
    print("-" * (width + 36))
    for name, tname, n in rows:
        print(f"{name:<{width}}{tname:<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total_params, "trainable_params": trainable}
