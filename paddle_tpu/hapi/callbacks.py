"""hapi callbacks (reference python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "VisualDL", "Monitor", "config_callbacks",
           "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            msg = f"step {step + 1}" + (f"/{self.steps}" if self.steps else "")
            for k, v in logs.items():
                if isinstance(v, numbers.Number):
                    msg += f" - {k}: {v:.4f}"
                elif isinstance(v, (list, tuple, np.ndarray)):
                    msg += f" - {k}: " + " ".join(f"{float(x):.4f}" for x in np.ravel(v)[:3])
            print(msg)

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            msg = "Eval"
            for k, v in logs.items():
                if isinstance(v, numbers.Number):
                    msg += f" - {k}: {v:.4f}"
                elif isinstance(v, (list, tuple, np.ndarray)):
                    msg += f" - {k}: " + " ".join(f"{float(x):.4f}" for x in np.ravel(v)[:3])
            print(msg)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater
        self.best = None
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple, np.ndarray)):
            value = float(np.ravel(value)[0])
        if self.best is None or self.monitor_op(value - self.min_delta, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()


class Monitor(Callback):
    """Per-step training telemetry (paddle_tpu.monitor.TrainerMonitor
    bridge): injects step_time_s / examples_per_sec / recompiles into the
    step logs, so ProgBarLogger prints them and VisualDL persists them.
    config_callbacks orders Monitor first so the telemetry lands in the
    logs dict before the loggers read it.
    """

    def __init__(self):
        super().__init__()
        from ..monitor import TrainerMonitor

        self.telemetry = TrainerMonitor()

    def on_train_begin(self, logs=None):
        self.telemetry.reset()

    def on_train_batch_begin(self, step, logs=None):
        self.telemetry.step_begin()

    def on_train_batch_end(self, step, logs=None):
        tele = self.telemetry.step_end(
            examples=self.params.get("batch_size"))
        if logs is not None and tele:
            logs["step_time_s"] = tele["step_time_s"]
            logs["recompiles"] = tele["recompiles"]
            if "examples_per_sec" in tele:
                logs["examples_per_sec"] = tele["examples_per_sec"]

    def summary(self):
        return self.telemetry.summary()


class VisualDL(Callback):
    """Scalar logging to a simple CSV (visualdl not in env)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.csv"), "a") as f:
            for k, v in logs.items():
                if isinstance(v, numbers.Number):
                    f.write(f"{self._step},{k},{v}\n")
        self._step += 1


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = list(cbks) + [LRScheduler()]
    # telemetry must run before the loggers that read its log entries
    mons = [c for c in cbks if isinstance(c, Monitor)]
    if mons:
        cbks = mons + [c for c in cbks if not isinstance(c, Monitor)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    params = {
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    }
    cbk_list.set_params(params)
    return cbk_list
