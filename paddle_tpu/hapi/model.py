"""hapi.Model — high-level train/eval/predict.

Parity: reference python/paddle/hapi/model.py:876 (Model.fit:1521,
evaluate:1752, predict:1855) with BOTH backends like the reference's
StaticGraphAdapter (:247) / DynamicGraphAdapter split:
- dynamic (default): eager per-batch, or a jit'd TrainStep that compiles
  forward+backward+update into one XLA program;
- static (`paddle.enable_static()` before prepare, Model(net, inputs,
  labels) with InputSpecs): prepare() builds main/eval Programs through
  the symbolic recorder, minimize() registers the update, and
  train/eval/predict_batch run through the static Executor.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.native import fast_step as _fast_step
from ..framework.core import AsyncLoss, Tensor, backward
from ..io import DataLoader, DevicePrefetcher
from ..metric import Metric
from ..monitor.trace import span as _trace_span
from ..nn.layer.layers import Layer
from . import callbacks as cbks_mod

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class _AutoFleetStep:
    """fleet.auto bridge (ISSUE 9): when ``fleet.init(strategy={"auto":
    True})`` is active, hapi.Model.fit routes its training step through a
    planner-built FleetEngine instead of the single-device jit.TrainStep —
    the unmodified script scales onto the planned dp x sharding x pp x mp
    mesh. The engine is built lazily at the first batch (the planner needs
    the global batch size); parameters write back into the eager network
    every step, so save()/state_dict() keep working."""

    def __init__(self, model):
        self._model = model
        self._engine = None

    @property
    def engine(self):
        return self._engine

    @property
    def _step_count(self):
        return self._engine.train_step._step_count if self._engine else 0

    def sync(self):
        pass  # no lazily-deferred slot mirrors on the engine path

    def __call__(self, *args):
        *ins, label = args
        if len(ins) != 1:
            raise ValueError(
                "the fleet.auto hapi path compiles single-input models; "
                "multi-input models need an explicit FleetEngine")
        x = ins[0]
        xa = x._data if isinstance(x, Tensor) else np.asarray(x)
        if self._engine is None:
            from ..distributed.fleet.base.fleet_base import fleet as _fleet
            from ..distributed.fleet.engine import FleetEngine

            self._engine = FleetEngine(
                self._model.network, self._model._optimizer,
                _fleet._strategy, loss_fn=self._model._loss,
                global_batch=int(xa.shape[0]))
        loss = self._engine.step((x, label))
        return loss if isinstance(loss, Tensor) else Tensor(loss)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._metrics = []
        self._optimizer = None
        self.stop_training = False
        self._train_step = None
        self._use_jit = True

    # -- configuration -------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None,
                jit_compile=True):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be Metric instances, got {m}")
        self._use_jit = jit_compile
        self._train_step = None
        self._static = None
        from .. import in_dynamic_mode

        if not in_dynamic_mode():
            self._prepare_static()

    # -- static-graph adapter (reference hapi/model.py:247) ------------------
    def _prepare_static(self):
        from .. import static
        from ..framework.enforce import PreconditionNotMetError

        if not self._inputs:
            raise PreconditionNotMetError(
                "hapi.Model in static mode needs input InputSpecs: "
                "Model(net, inputs=[InputSpec(...)], labels=[...]).",
                hint="the static program is built from the declared shapes")

        def as_data(spec, i, prefix):
            name = getattr(spec, "name", None) or f"{prefix}{i}"
            return static.data(name, list(spec.shape),
                               dtype=getattr(spec, "dtype", "float32"))

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ins = [as_data(s, i, "x") for i, s in
                   enumerate(_to_list(self._inputs))]
            labels = [as_data(s, i, "label") for i, s in
                      enumerate(_to_list(self._labels))]
            outs = self.network(*ins)
            loss_var = None
            if self._loss is not None and labels:
                loss_var = self._loss(outs, *labels)
                if self._optimizer is not None:
                    self._optimizer.minimize(loss_var)
        self._static = {
            "main": main,
            "eval": main.clone(for_test=True),
            "exe": static.Executor(),
            "in_names": [t.name for t in ins],
            "label_names": [t.name for t in labels],
            "outs": outs,
            "loss": loss_var,
        }

    def _static_feed(self, inputs, labels):
        st = self._static
        feed = {n: (x._data if isinstance(x, Tensor) else np.asarray(x))
                for n, x in zip(st["in_names"], inputs)}
        for n, x in zip(st["label_names"], labels):
            feed[n] = x._data if isinstance(x, Tensor) else np.asarray(x)
        return feed

    # -- core steps ----------------------------------------------------------
    def _build_train_step(self, sentinel=None):
        from ..distributed.fleet.base.fleet_base import fleet as _fleet

        strat = getattr(_fleet, "_strategy", None)
        if strat is not None and getattr(strat, "auto", False) \
                and sentinel is None:
            # fleet.auto active: the planner-built engine IS the step
            return _AutoFleetStep(self)

        from ..jit import TrainStep

        loss_layer = self._loss

        def loss_fn(run_model, *batch):
            # convention: last element is the label
            *ins, label = batch
            out = run_model(*ins)
            return loss_layer(out, label)

        return TrainStep(self.network, loss_fn, self._optimizer,
                         sentinel=sentinel)

    def train_batch(self, inputs, labels=None, update=True, sync=True):
        """One training step. ``sync=False`` (the fit() fast path) returns
        the loss as an un-awaited AsyncLoss handle instead of a float —
        the device step is dispatched and the host moves on; reading the
        handle is the sync point."""
        with _trace_span("Model.train_batch", cat="step"):
            return self._train_batch_impl(inputs, labels, update, sync)

    def _train_batch_impl(self, inputs, labels=None, update=True, sync=True):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self._static is not None:
            st = self._static
            prog = st["main"] if update else st["eval"]
            (loss,) = st["exe"].run(
                prog, feed=self._static_feed(inputs, labels),
                fetch_list=[st["loss"]])
            return [float(np.asarray(loss))]
        if self._use_jit and update and len(labels) == 1:
            if self._train_step is None:
                self._train_step = self._build_train_step()
            loss = self._train_step(*inputs, labels[0])
            from ..optimizer.lr import LRScheduler

            if isinstance(self._optimizer._learning_rate, LRScheduler):
                pass  # stepped by LRScheduler callback
            if not sync and isinstance(loss, AsyncLoss):
                return [loss]
            return [float(loss.numpy())]
        outputs = self.network(*inputs)
        loss = self._loss(outputs, *labels)
        backward(loss)
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self._static is not None:
            st = self._static
            outs = st["outs"] if isinstance(st["outs"], (list, tuple)) \
                else [st["outs"]]
            fetch = ([st["loss"]] if (st["loss"] is not None and labels)
                     else []) + list(outs)
            vals = st["exe"].run(st["eval"],
                                 feed=self._static_feed(inputs, labels),
                                 fetch_list=fetch)
            metrics = []
            k = 0
            if st["loss"] is not None and labels:
                metrics.append(float(np.asarray(vals[0])))
                k = 1
            out_t = [Tensor(v) for v in vals[k:]]
            out_t = out_t[0] if len(out_t) == 1 else out_t
            for metric in self._metrics:
                corr = metric.compute(out_t, *labels)
                metric.update(corr)
            return metrics
        outputs = self.network(*inputs)
        metrics = []
        if self._loss is not None and labels:
            loss = self._loss(outputs, *labels)
            metrics.append(float(loss.numpy()))
        for metric in self._metrics:
            corr = metric.compute(outputs, *labels)
            metric.update(corr)
        return metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _to_list(inputs)
        if self._static is not None:
            st = self._static
            outs = st["outs"] if isinstance(st["outs"], (list, tuple)) \
                else [st["outs"]]
            vals = st["exe"].run(st["eval"],
                                 feed=self._static_feed(inputs, []),
                                 fetch_list=list(outs))
            return [np.asarray(v) for v in vals]
        out = self.network(*inputs)
        if isinstance(out, (list, tuple)):
            return [o.numpy() for o in out]
        return [out.numpy()]

    # -- loops ---------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, resilience=None):
        train_loader = self._make_loader(train_data, batch_size, shuffle)
        eval_loader = self._make_loader(eval_data, batch_size, False)
        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [n for m in self._metrics for n in _to_list(m.name())])
        self.stop_training = False
        # resilience=... wires a TrainGuardian around the jitted step: the
        # in-jit sentinel skips poisoned updates, repeated trips rewind to
        # the guardian's host snapshot (the epoch restarts with a fresh —
        # re-seeded when shuffle=True — batch order), SIGTERM forces a
        # priority checkpoint and stops cleanly. Pass a TrainGuardian, a
        # kwargs dict for one, or True for defaults.
        guardian = None
        guardian_owned = False
        if resilience is not None and resilience is not False \
                and getattr(self, "_static", None) is None and self._use_jit:
            from ..resilience.guardian import TrainGuardian

            if isinstance(resilience, TrainGuardian):
                guardian = resilience
            else:
                kwargs = {} if resilience is True else dict(resilience)
                guardian = TrainGuardian(**kwargs)
                guardian_owned = True   # fit created it -> fit closes it
            if self._train_step is None:
                self._train_step = self._build_train_step(
                    sentinel=guardian.sentinel_config)
            if guardian._obj is None:
                guardian.attach(self._train_step)
            guardian.install_preemption_handler()
            guardian.restore_latest()
        cbks.on_train_begin({})
        # FLAGS_fast_step input-and-step fast path: batches are device_put
        # one step ahead (double buffering — the H2D copy of batch N+1
        # overlaps step N) and the per-step loss is kept as an un-awaited
        # AsyncLoss handle; the host only blocks on it at log_freq
        # boundaries and at epoch end, so steps pipeline instead of paying
        # a device round-trip each (step_async_syncs counts the blocks).
        fast = _fast_step[0] and getattr(self, "_static", None) is None
        loss_val = None
        epoch = 0
        while epoch < epochs:
            cbks.on_epoch_begin(epoch, {})
            epoch_iter = (DevicePrefetcher(train_loader, size=2) if fast
                          else train_loader)
            pending = None
            restart_epoch = False
            for step, batch in enumerate(epoch_iter):
                cbks.on_train_batch_begin(step, {})
                *ins, label = batch if isinstance(batch, (list, tuple)) else (batch,)
                losses = self.train_batch(ins, [label], sync=not fast)
                raw = losses[0]
                if isinstance(raw, Tensor):
                    pending = raw
                    if step % log_freq == 0 or (
                            num_iters is not None and step + 1 >= num_iters):
                        loss_val = float(raw)
                else:
                    loss_val = raw
                logs = {"loss": loss_val}
                cbks.on_train_batch_end(step, logs)
                if guardian is not None:
                    action = guardian.after_step(
                        self._train_step._step_count - 1, raw)
                    if action in ("rollback", "resize"):
                        # state rewound to the snapshot (possibly on a
                        # re-planned mesh after host loss); replay the
                        # epoch with a fresh batch order
                        pending = None
                        restart_epoch = True
                        break
                    if action == "preempt":
                        self.stop_training = True
                        break
                if num_iters is not None and step + 1 >= num_iters:
                    break
            if restart_epoch:
                continue
            if pending is not None:  # epoch-end logs carry the real value
                loss_val = float(pending)
                logs = {"loss": loss_val}
            self._sync_train_step()
            cbks.on_epoch_end(epoch, logs if steps else {})
            if eval_loader is not None and (epoch + 1) % eval_freq == 0 \
                    and not self.stop_training:
                self.evaluate(eval_loader, batch_size=batch_size, verbose=verbose,
                              callbacks=cbks)
            if self.stop_training:
                break
            epoch += 1
        self._sync_train_step()
        if guardian is not None:
            # the async snapshot thread must not outlive the fit that
            # spawned it (a pending background save at interpreter exit
            # dies in orbax's shut-down executor); user-passed guardians
            # stay open — their loop may continue — but drain here
            if guardian_owned:
                guardian.close()
            else:
                guardian.drain_snapshots()
        cbks.on_train_end({})

    def _sync_train_step(self):
        """Flush the fast path's lazily-deferred optimizer-slot mirrors so
        state_dict()/save() readers see current device state."""
        if self._train_step is not None and hasattr(self._train_step, "sync"):
            self._train_step.sync()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        if isinstance(callbacks, cbks_mod.CallbackList):
            cbks = callbacks
        else:
            cbks = cbks_mod.config_callbacks(callbacks, model=self, verbose=verbose,
                                             mode="eval")
        cbks.on_eval_begin({})
        losses = []
        for step, batch in enumerate(loader):
            *ins, label = batch if isinstance(batch, (list, tuple)) else (batch,)
            m = self.eval_batch(ins, [label])
            if m:
                losses.append(m[0])
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for metric in self._metrics:
            res = metric.accumulate()
            names = _to_list(metric.name())
            vals = _to_list(res)
            for n, v in zip(names, vals):
                logs[n] = v
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            ins = list(batch) if isinstance(batch, (list, tuple)) else [batch]
            # match reference input-arity handling: an explicit inputs spec
            # wins; otherwise a loss-prepared model treats the trailing
            # element of an (x, ..., y) dataset item as the label
            if self._inputs is not None:
                ins = ins[:len(_to_list(self._inputs))]
            elif self._loss is not None and len(ins) > 1:
                ins = ins[:-1]
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save

        self._sync_train_step()
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load

        sd = load(path + ".pdparams")
        self.network.set_state_dict(sd)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtype)
