"""paddle_tpu.resilience — self-healing training.

The reference ships fault tolerance as a first-class capability (fleet
elastic relaunch-on-membership-change, AutoCheckpoint auto-resume,
FLAGS_check_nan_inf); this package composes our equivalents —
CheckpointManager, ElasticManager, the compiled train step — into a loop
that survives the failure modes a preemptible TPU fleet actually hits,
and makes every one of them deterministically reproducible on CPU via
``FLAGS_fault_inject`` (:mod:`.faults`).

Failure modes and their handling:

===============  ==========================  ================================
failure          detected by                 handled by
===============  ==========================  ================================
NaN/Inf loss or  in-jit sentinel             in-jit update gate skips the
gradients        (:mod:`.sentinel`           step (params untouched); trip
                 finiteness check)           counted on device
loss/grad-norm   sentinel EMA z-score        same skip gate; escalates like
spike            (``z > z_thresh``)          a NaN trip
repeated trips   TrainGuardian ladder        rollback to the host-offloaded
(> skip_limit)   (:mod:`.guardian`)          snapshot + re-seeded data order
trips past       TrainGuardian               :class:`TrainingAborted` —
max_rollbacks                                stop burning accelerator time
process crash    next launch                 ``restore_latest()`` resumes
                                             from the newest intact on-disk
                                             checkpoint (corrupt step dirs
                                             skipped with a warning)
preemption       SIGTERM handler             priority orbax save +
(SIGTERM)                                    ``ElasticStatus.RESTART`` mark
stalled step     watchdog thread vs the      ``watchdog_stalls`` gauge,
                 heartbeat gauge             all-thread stack dump, trace
                                             flush
flaky ckpt I/O   OSError during save         retry with exponential backoff
                                             (framework/checkpoint.py)
host loss        heartbeat staleness /       pod-coordinated ELASTIC RESIZE
(pod)            tombstone via               (:mod:`.pod` + guardian
                 :class:`PodCoordinator`     ``rebuild=``): fleet.auto
                                             replans over the survivors,
                                             the agreed snapshot reshards
                                             through the ZeRO checkpoint
                                             round-trip, training resumes
KV-store         OSError from the shared     FileKVStore put retry budget;
partition        FileKVStore                 liveness probes report
                                             "unknowable", never all-dead
poisoned decode  per-tick NaN/latency        serving engine auto-restart:
tick (serving)   sentinel                    poisoned requests fail, healthy
                 (``serving/engine.py        streams resume token-identical
                 watchdog=``)                from replayed history
===============  ==========================  ================================

The pod escalation ladder, cheapest rung first:
**skip** (in-jit gate) -> **rollback** to the pod-agreed snapshot step
(+LR backoff) -> **resize** over the surviving hosts ->
:class:`TrainingAborted`.

Gauges: ``faults_injected``, ``sentinel_trips``, ``rollbacks``,
``preempt_saves``, ``watchdog_stalls``, ``guardian_heartbeat_ms``,
``pod_hosts_alive``, ``elastic_resizes``, ``serving_watchdog_trips``,
``serving_watchdog_restarts``.
Trace spans: ``resilience.snapshot`` / ``resilience.snapshot_async`` /
``resilience.rollback`` / ``resilience.pod_agree`` /
``resilience.resize`` / ``resilience.preempt_save`` +
``resilience.trip`` instants — ``tools/trace_report.py`` renders them as
a resilience timeline with a per-host pod section.

Wired in: ``hapi.Model.fit(resilience=...)`` and
``FleetEngine(..., sentinel=...)`` + ``TrainGuardian.attach(engine)``;
any hand-written loop can use :class:`TrainGuardian` directly (see its
docstring for the canonical loop shape).
"""
from . import faults  # noqa: F401  (registers the FLAGS_fault_inject watcher)
from .faults import FAULTS, FaultSpec, InjectedCrash, configure_faults
from . import sentinel  # noqa: F401

__all__ = [
    "faults", "sentinel", "FAULTS", "FaultSpec", "InjectedCrash",
    "configure_faults", "TrainGuardian", "TrainingAborted", "guardian",
    "PodCoordinator", "PodAgreementError", "pod",
]


def __getattr__(name):
    # guardian pulls in framework.checkpoint (orbax) — lazy so fault
    # hooks in hot paths never pay for it; pod stays lazy with it
    if name in ("TrainGuardian", "TrainingAborted", "guardian"):
        import importlib

        mod = importlib.import_module(".guardian", __name__)
        if name == "guardian":
            return mod
        return getattr(mod, name)
    if name in ("PodCoordinator", "PodAgreementError", "pod"):
        import importlib

        mod = importlib.import_module(".pod", __name__)
        if name == "pod":
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
