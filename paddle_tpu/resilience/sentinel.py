"""In-jit per-step health verdict.

The sentinel runs INSIDE the already-jitted train step (the analog of the
reference's fused check_finite_and_unscale op rather than its host-side
FLAGS_check_nan_inf walk): it computes loss/global-grad-norm finiteness
plus an EMA z-score spike test on the grad norm, gates the whole
parameter/optimizer update on the verdict (a tripped step is a no-op,
GradScaler-style), and accumulates a device-resident trip counter. The
verdict rides the step's outputs — carried on :class:`AsyncLoss` as
``.health`` — so the FLAGS_fast_step zero-extra-syncs property is
preserved: nothing here forces a host read; the guardian decides when to
look.

State (replicated device scalars, carried across steps)::

    {"mean": EMA of grad norm, "var": EMA of squared deviation,
     "n": healthy steps observed, "trips": cumulative verdict trips,
     "last_trip": last step's verdict}

Verdict = NOT finite(loss, grad_norm) OR (n >= warmup AND
|gnorm - mean| / sqrt(var + eps) > z_thresh). The EMA only absorbs
healthy steps, so a spike does not poison the baseline it is measured
against.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["default_config", "init_state", "update", "global_grad_norm",
           "gate", "logits_finite"]


def logits_finite(logits) -> jnp.ndarray:
    """Per-row all-finite verdict over a ``(batch, vocab)`` logits block
    — the SERVING side's NaN sentinel. The engine's decode ticks return
    it (one bool per slot) when ``InferenceEngine(watchdog=...)`` is
    armed, so a poisoned stream is identified inside the already-running
    program, the same zero-extra-work discipline as the training
    sentinel's in-jit verdict."""
    return jnp.all(jnp.isfinite(logits), axis=-1)


def default_config(z_thresh: float = 8.0, warmup: int = 20,
                   ema_decay: float = 0.98) -> Dict[str, float]:
    """Sentinel hyperparameters. ``z_thresh`` in EMA standard deviations;
    ``warmup`` healthy steps before the spike test arms (finiteness is
    always armed); ``ema_decay`` the baseline's smoothing factor."""
    return {"z_thresh": float(z_thresh), "warmup": int(warmup),
            "ema_decay": float(ema_decay)}


def normalize_config(cfg) -> Dict[str, float]:
    """None/True/partial-dict → full config."""
    if cfg is None or cfg is True:
        return default_config()
    out = default_config()
    out.update({k: v for k, v in dict(cfg).items() if k in out})
    return out


def init_state() -> Dict[str, jnp.ndarray]:
    return {"mean": jnp.float32(0.0), "var": jnp.float32(0.0),
            "n": jnp.int32(0), "trips": jnp.int32(0),
            "last_trip": jnp.bool_(False)}


def global_grad_norm(grads) -> jnp.ndarray:
    """fp32 global L2 norm over a grad pytree (same reduction the
    sharded program lowers to cross-device psums)."""
    sq = jnp.float32(0.0)
    for g in jax.tree_util.tree_leaves(grads):
        sq = sq + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return jnp.sqrt(sq)


def update(state, loss, gnorm, cfg) -> Dict[str, jnp.ndarray]:
    """Pure step: (state, loss, grad norm) -> new state (including the
    verdict in ``last_trip``). Traced inside the jitted train step."""
    loss32 = jnp.asarray(loss, jnp.float32)
    g = jnp.asarray(gnorm, jnp.float32)
    finite = jnp.isfinite(loss32) & jnp.isfinite(g)
    z = jnp.abs(g - state["mean"]) / jnp.sqrt(state["var"] + 1e-12)
    spike = (state["n"] >= int(cfg["warmup"])) & (z > float(cfg["z_thresh"]))
    trip = (~finite) | spike
    d = float(cfg["ema_decay"])
    new_mean = d * state["mean"] + (1.0 - d) * g
    new_var = d * state["var"] + (1.0 - d) * jnp.square(g - state["mean"])
    healthy = ~trip
    return {
        "mean": jnp.where(healthy, new_mean, state["mean"]),
        "var": jnp.where(healthy, new_var, state["var"]),
        "n": jnp.where(healthy, state["n"] + 1, state["n"]),
        "trips": state["trips"] + trip.astype(jnp.int32),
        "last_trip": trip,
    }


def gate(trip, new_tree, old_tree):
    """GradScaler-style skip: keep ``old_tree`` wherever the verdict
    tripped (``where`` select — a skipped step costs nothing extra)."""
    if new_tree is None:
        return None
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(trip, b, a), new_tree, old_tree)


def read_health(state) -> Optional[dict]:
    """Host-side view of a sentinel state (device scalars, reading
    blocks): {"trip": bool, "trips": int, "gnorm_mean": float}."""
    if state is None:
        return None
    return {"trip": bool(state["last_trip"]),
            "trips": int(state["trips"]),
            "gnorm_mean": float(state["mean"])}
