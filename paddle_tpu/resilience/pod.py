"""Pod-wide agreement + host-loss detection for coordinated resilience.

A single-host TrainGuardian (PR 5) rewinds to ITS snapshot; on a pod that
is not enough — every host must restore the SAME step or the replicated
optimizer states diverge and the replay stops being bit-exact. This
module supplies the two pod-level primitives the guardian composes:

- :class:`PodCoordinator.agree_rollback` — a propose/commit/ack protocol
  over the elastic :class:`~paddle_tpu.distributed.elastic.FileKVStore`
  (the same shared directory the ElasticManager heartbeats through, with
  the same transient-OSError retry discipline). Each host proposes the
  snapshot steps it holds; once every live host has proposed, the commit
  is the HIGHEST step present in every proposal (deterministic, so the
  racing committers all write the same value and the atomic-rename put
  makes the overwrite benign); a laggard host that arrives after the
  commit simply adopts it. An ack barrier holds everyone at the commit
  until the whole pod has restored, so the replay restarts aligned.
- :class:`PodCoordinator.lost_hosts` — membership verdict from the
  ElasticManager's monotonic heartbeat staleness (plus tombstones), with
  the ``host_loss@step=N:host=H`` / ``kv_partition@step=N:secs=S`` fault
  specs claimed here so the resize and partition paths are testable
  without real multi-host runs. A store partition makes liveness
  UNKNOWABLE, not everyone-dead: reads that raise OSError report no
  losses for that probe.

The protocol keys live under ``jobs/<job>/rollback/<round>/`` — one
round per pod-wide rollback or resize, numbered locally in lockstep
(every host initiates the same rollback: the sentinel verdict that
triggers it is replicated device state).
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

from ..monitor import trace as _mtrace
from . import faults as _faults

__all__ = ["PodCoordinator", "PodAgreementError"]


class PodAgreementError(RuntimeError):
    """The pod could not agree a rollback step (timeout waiting for
    proposals/acks, or no snapshot step common to every host)."""


class PodCoordinator:
    """One per host; all instances of a job share the FileKVStore.

    Args:
      kv: the shared :class:`FileKVStore` (NFS/GCS-fuse dir on real pods,
        a tmpdir in tests).
      job_id: job namespace inside the store.
      host: THIS host's name.
      hosts: full expected pod membership (all hosts, this one included).
      elastic: optional :class:`ElasticManager` for heartbeat-staleness
        liveness; without it only tombstones (``mark_dead``) count as
        losses.
      device_map: ``{host: [jax devices]}`` — which devices each host
        contributes to the mesh. Only needed for elastic resize, where
        the surviving device set seeds the fleet.auto replan. Its keys
        may be a SUPERSET of ``hosts``: the single-process virtual-mesh
        rig drives one coordinating agent that watches several simulated
        device-hosts (the membership checks cover the union).
      timeout / poll: agreement deadline and poll cadence (seconds).
    """

    def __init__(self, kv, job_id: str, host: str,
                 hosts: Sequence[str], elastic=None,
                 device_map: Optional[Dict[str, list]] = None,
                 timeout: float = 30.0, poll: float = 0.005):
        self.kv = kv
        self.job_id = str(job_id)
        self.host = str(host)
        self.hosts: List[str] = sorted(str(h) for h in hosts)
        if self.host not in self.hosts:
            raise ValueError(f"host {self.host!r} not in pod {self.hosts}")
        self.elastic = elastic
        self.device_map = dict(device_map or {})
        self.timeout = float(timeout)
        self.poll = float(poll)
        self.prefix = f"jobs/{self.job_id}"
        self._round = 0

    # -- kv helpers (partition-tolerant) -------------------------------------
    def _get(self, key: str) -> Optional[bytes]:
        try:
            return self.kv.get(key)
        except OSError:
            return None

    def _get_prefix(self, prefix: str) -> dict:
        try:
            return self.kv.get_prefix(prefix)
        except OSError:
            return {}

    def _put(self, key: str, value) -> bool:
        try:
            self.kv.put(key, value)
            return True
        except OSError:
            return False   # partition outlived the retry budget: re-poll

    # -- rollback agreement --------------------------------------------------
    def agree_rollback(self, held_steps: Sequence[int],
                       expected: Optional[Sequence[str]] = None) -> int:
        """Propose the snapshot steps this host holds; return the
        pod-committed rollback step (the highest step EVERY live host
        holds). Blocks until commit + full ack barrier, or raises
        :class:`PodAgreementError` at ``timeout``."""
        self._round += 1
        r = self._round
        base = f"{self.prefix}/rollback/{r}"
        expected = sorted(expected) if expected is not None else self.hosts
        proposal = json.dumps(sorted(int(s) for s in set(held_steps)))
        deadline = time.monotonic() + self.timeout
        proposed = False
        committed: Optional[int] = None
        with _mtrace.span("resilience.pod_agree", cat="resilience",
                          args={"host": self.host, "round": r}):
            while time.monotonic() < deadline:
                if not proposed:
                    proposed = self._put(f"{base}/prop/{self.host}", proposal)
                raw = self._get(f"{base}/commit")
                if raw is not None:
                    # a laggard adopts the committed step even if its own
                    # proposal never made the decision
                    committed = int(raw.decode())
                    break
                props = self._get_prefix(f"{base}/prop")
                if proposed and len(props) >= len(expected):
                    sets = [set(json.loads(v.decode()))
                            for v in props.values()]
                    common = set.intersection(*sets) if sets else set()
                    step = max(common) if common else -1
                    # every decider computes the same value from the same
                    # full proposal set — concurrent commits are idempotent
                    if self._put(f"{base}/commit", str(step)):
                        committed = step
                        break
                time.sleep(self.poll)
        if committed is None:
            raise PodAgreementError(
                f"pod rollback round {r}: no commit within "
                f"{self.timeout}s (have "
                f"{sorted(self._get_prefix(f'{base}/prop'))}, need "
                f"{expected})")
        if committed < 0:
            raise PodAgreementError(
                f"pod rollback round {r}: no snapshot step common to "
                f"every host")
        # ack barrier: nobody replays until the whole pod has restored
        self._put(f"{base}/ack/{self.host}", b"1")
        while time.monotonic() < deadline:
            if len(self._get_prefix(f"{base}/ack")) >= len(expected):
                return committed
            time.sleep(self.poll)
        raise PodAgreementError(
            f"pod rollback round {r}: ack barrier timed out at step "
            f"{committed}")

    # -- membership ----------------------------------------------------------
    def maybe_heartbeat(self) -> None:
        """Refresh this host's lease (partition-tolerant: a blip rides the
        put retry budget; a longer one just skips the beat)."""
        if self.elastic is not None:
            try:
                self.elastic.heartbeat(self.host)
            except OSError:
                pass

    def lost_hosts(self, step: Optional[int] = None) -> List[str]:
        """Hosts of this pod that are gone (tombstoned, or heartbeat-stale
        when an ElasticManager is attached). ``step`` additionally claims
        the step-keyed ``host_loss`` / ``kv_partition`` fault specs, so an
        injected pod failure surfaces through the SAME detection path a
        real one would."""
        if step is not None and _faults.ENABLED[0]:
            f = _faults.FAULTS.take("kv_partition", step)
            if f is not None:
                from ..monitor import stats as _mstats

                _mstats.FAULTS_INJECTED.add()
                _faults.begin_kv_partition(f.secs)
            f = _faults.FAULTS.take("host_loss", step)
            if f is not None:
                from ..monitor import stats as _mstats

                _mstats.FAULTS_INJECTED.add()
                try:
                    if self.elastic is not None:
                        self.elastic.mark_dead(f.host)
                    else:
                        self._put(f"{self.prefix}/dead/{f.host}", b"1")
                except OSError:
                    pass   # partitioned store: the tombstone lands later
        watch = sorted(set(self.hosts) | set(self.device_map))
        dead: set = set()
        if self.elastic is not None:
            try:
                dead.update(self.elastic.dead_hosts())
                alive = set(self.elastic.alive_hosts())
                dead.update(h for h in watch
                            if h not in alive and self.elastic.last_seen_age(h)
                            is not None)
            except OSError:
                return []   # partition: liveness unknowable, not all-dead
        else:
            dead.update(k.rsplit("/", 1)[1] for k in
                        self._get_prefix(f"{self.prefix}/dead"))
        return sorted(h for h in watch if h in dead)

    def remove_hosts(self, lost: Sequence[str]) -> List[str]:
        """Shrink the expected membership after a resize; returns the
        surviving coordinating-host list."""
        lost_set = set(lost)
        self.hosts = [h for h in self.hosts if h not in lost_set]
        for h in lost_set:
            self.device_map.pop(h, None)
        return list(self.hosts)

    def surviving_devices(self, lost: Sequence[str]) -> list:
        """Devices contributed by the device-map hosts NOT in ``lost``
        (device_map order preserved) — the fleet.auto replan input."""
        lost_set = set(lost)
        out = []
        for h in self.device_map:
            if h not in lost_set:
                out.extend(self.device_map[h])
        return out
