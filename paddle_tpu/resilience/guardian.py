"""TrainGuardian — snapshot / skip / rollback / preempt / watchdog.

The guardian composes the pieces that already existed in isolation
(CheckpointManager, ElasticManager, the sentinel verdict, the stat
gauges) into a training loop that survives NaNs, stalls, crashes and
preemption:

- **rolling snapshot**: every ``snapshot_every`` healthy steps the full
  training state (params, optimizer state, buffers, scaler, RNG, step
  count) is offloaded to HOST memory — O(model) RAM, no filesystem — so
  a rollback never waits on storage. ``resilience.snapshot`` trace span.
- **escalation ladder** on sentinel trips (read at ``check_every``
  cadence from the device-resident trip counter): the in-jit gate has
  already SKIPPED the poisoned update (GradScaler-style, params
  untouched); after ``skip_limit`` consecutive tripped steps the
  guardian REWINDS to the last snapshot (``resilience.rollback`` span,
  ``rollbacks`` gauge) and bumps ``data_seed`` so the caller re-seeds
  its data order; after ``max_rollbacks`` rewinds it raises
  :class:`TrainingAborted` — a babysitter would have paged a human long
  ago.
- **preemption**: ``install_preemption_handler()`` catches SIGTERM (the
  Cloud TPU preemption notice); the next ``after_step`` forces a
  priority orbax save (``preempt_saves`` gauge), marks
  ``ElasticStatus.RESTART`` in the elastic KV store when an
  ElasticManager is attached, and returns ``"preempt"`` so the loop can
  exit cleanly. The relaunched worker auto-resumes via
  :meth:`restore_latest`.
- **watchdog**: a daemon thread watches the heartbeat gauge
  (``guardian_heartbeat_ms``, bumped by every ``after_step``); a step
  silent for ``watchdog_timeout`` seconds bumps ``watchdog_stalls``,
  dumps all thread stacks, and flushes the chrome trace for post-mortem.

Usage::

    g = TrainGuardian(step, ckpt_dir=dir, snapshot_every=20,
                      sentinel=True, watchdog_timeout=300)
    start = g.restore_latest() or 0          # crash auto-resume
    i = start
    while i < n_steps:
        loss = step(batch_at(i, seed=g.data_seed))
        action = g.after_step(i, loss)
        if action == "rollback":
            i = g.resume_step                # replay from the snapshot
            continue
        if action == "preempt":
            break                            # priority save already done
        i += 1
    g.close()
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
import warnings
from typing import Any, Optional

import numpy as np

from ..monitor import stats as _mstats
from ..monitor import trace as _mtrace
from . import sentinel as _sentinel

__all__ = ["TrainGuardian", "TrainingAborted"]


class TrainingAborted(RuntimeError):
    """The escalation ladder ran out: more than ``max_rollbacks`` rewinds
    (or an unrecoverable restore failure). Training must stop."""


def _host_tree(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


class TrainGuardian:
    """Wraps a train step (jit.TrainStep, DistributedTrainStep, or a
    FleetEngine) with self-healing behavior. See the module docstring for
    the ladder; all thresholds are per-instance knobs."""

    def __init__(self, step=None, ckpt_dir: Optional[str] = None,
                 snapshot_every: int = 25, skip_limit: int = 2,
                 max_rollbacks: int = 3, check_every: int = 1,
                 sentinel=True, watchdog_timeout: Optional[float] = None,
                 elastic=None, save_interval_steps: int = 1,
                 max_to_keep: int = 3):
        self.snapshot_every = max(1, int(snapshot_every))
        self.skip_limit = int(skip_limit)
        self.max_rollbacks = int(max_rollbacks)
        self.check_every = max(1, int(check_every))
        self.sentinel_config = (_sentinel.normalize_config(sentinel)
                                if sentinel else None)
        self.watchdog_timeout = watchdog_timeout
        self.elastic = elastic
        self.data_seed = 0          # bumped by every rollback
        self.ckpt_dir = ckpt_dir
        self._ckpt = None
        self._ckpt_opts = (int(save_interval_steps), int(max_to_keep))
        self._obj = None            # as attached (may be a FleetEngine)
        self._step_obj = None       # the underlying train step
        self._snap = None           # (step_idx, host state tree)
        self._consec = 0            # consecutive tripped check windows
        self._trips_seen = 0
        self._rollbacks = 0
        self._preempted = False
        self._prev_sigterm = None
        # _last_beat is written by BOTH the training thread (_beat) and
        # the watchdog thread (stall re-arm) — graftlint GL003; the lock
        # also makes the read-compare-rearm in the watchdog atomic so a
        # beat landing mid-check cannot be overwritten by the re-arm
        self._beat_lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._watchdog = None
        self._watchdog_stop = threading.Event()
        self._closed = False
        if step is not None:
            self.attach(step)

    # -- attachment ---------------------------------------------------------
    def attach(self, obj) -> "TrainGuardian":
        """Bind a train step or FleetEngine; takes the initial snapshot so
        a rollback is possible from step 0."""
        self._obj = obj
        self._step_obj = getattr(obj, "train_step", obj)
        if self.ckpt_dir is not None and self._ckpt is None:
            from ..framework.checkpoint import CheckpointManager

            interval, keep = self._ckpt_opts
            self._ckpt = CheckpointManager(
                self.ckpt_dir, save_interval_steps=interval,
                max_to_keep=keep, async_save=False)
        self.snapshot(-1)
        if self.watchdog_timeout:
            self._start_watchdog()
        return self

    # -- state capture / install -------------------------------------------
    def _capture(self) -> dict:
        """Full training state as a pytree of arrays (host- or
        device-resident, caller's choice of offload)."""
        import jax

        from ..framework.random import get_rng_state

        s = self._step_obj
        out: dict = {"rng": jax.random.key_data(get_rng_state()),
                     # 0-d ndarray: orbax rejects numpy scalar types
                     "step_count": np.asarray(
                         getattr(s, "_step_count", 0), np.int64)}
        if hasattr(s, "params") and hasattr(s, "opt_state"):
            out["params"] = s.params
            out["opt_state"] = s.opt_state
            if getattr(s, "aux", None) is not None:
                out["aux"] = s.aux
            if getattr(s, "scaler_state", None) is not None:
                out["scaler"] = s.scaler_state
        elif hasattr(s, "_params") and hasattr(s, "_slot_values"):
            out["params"] = {k: p._data for k, p in s._params.items()}
            out["slots"] = {k: list(v) for k, v in s._slot_values.items()}
            bufs = {k: b._data for k, b in s.model.named_buffers()
                    if b is not None}
            if bufs:
                out["buffers"] = bufs
        else:
            raise TypeError(
                f"TrainGuardian cannot snapshot {type(s).__name__}: need a "
                "DistributedTrainStep-like (.params/.opt_state) or "
                "jit.TrainStep-like (._params/._slot_values) object")
        st = getattr(s, "sentinel_state", None)
        if st is not None:
            out["sentinel"] = st
        return out

    def _install(self, state: dict) -> None:
        import jax

        from ..framework.random import set_rng_state

        s = self._step_obj
        if "rng" in state:
            set_rng_state(jax.random.wrap_key_data(
                np.asarray(state["rng"]).astype(np.uint32)))
        if hasattr(s, "params") and hasattr(s, "opt_state"):
            put = lambda t, sh: (jax.device_put(t, sh) if sh is not None
                                 else jax.device_put(t))
            s.params = put(state["params"], getattr(s, "_param_sh", None))
            s.opt_state = put(state["opt_state"], getattr(s, "_opt_sh", None))
            if "aux" in state and getattr(s, "aux", None) is not None:
                s.aux = put(state["aux"], getattr(s, "_aux_sh", None))
            if "scaler" in state and getattr(s, "scaler_state", None) is not None:
                s.scaler_state = jax.device_put(state["scaler"])
        else:
            for k, arr in state["params"].items():
                s._params[k]._data = jax.device_put(np.asarray(arr))
            for k, vals in state.get("slots", {}).items():
                s._slot_values[k] = [jax.device_put(np.asarray(v))
                                     for v in vals]
                s.optimizer._set_slots(s._params[k], s._slot_values[k])
            if state.get("buffers"):
                named = {k: b for k, b in s.model.named_buffers()
                         if b is not None}
                for k, arr in state["buffers"].items():
                    named[k]._data = jax.device_put(np.asarray(arr))
        if "step_count" in state and hasattr(s, "_step_count"):
            s._step_count = int(state["step_count"])
        if "sentinel" in state and getattr(s, "sentinel_state", None) is not None:
            s.sentinel_state = jax.device_put(
                jax.tree_util.tree_map(np.asarray, state["sentinel"]))
        # FleetEngine: mirror the restored device params back into the
        # eager Layer so state_dict/save readers stay consistent
        if self._obj is not self._step_obj:
            eng = self._obj
            if hasattr(eng, "_write_back"):
                eng._write_back(self._step_obj.params)
            if hasattr(eng, "_write_back_buffers"):
                eng._write_back_buffers(getattr(self._step_obj, "aux", None))

    # -- snapshot / rollback -------------------------------------------------
    def snapshot(self, step_idx: int) -> None:
        """Host-offloaded rolling snapshot (keeps exactly one)."""
        with _mtrace.span("resilience.snapshot", cat="resilience",
                          args={"step": step_idx}):
            self._snap = (int(step_idx), _host_tree(self._capture()))

    @property
    def resume_step(self) -> int:
        """First step index to (re)run after a rollback/restore."""
        return (self._snap[0] + 1) if self._snap is not None else 0

    def rollback(self) -> int:
        """Rewind to the last snapshot; returns the step index to resume
        from. Raises :class:`TrainingAborted` past ``max_rollbacks``."""
        if self._snap is None:
            raise TrainingAborted("sentinel tripped but no snapshot exists")
        self._rollbacks += 1
        _mstats.ROLLBACKS.add()
        if self._rollbacks > self.max_rollbacks:
            raise TrainingAborted(
                f"aborting: {self._rollbacks} rollbacks exceed "
                f"max_rollbacks={self.max_rollbacks}")
        step_idx, state = self._snap
        with _mtrace.span("resilience.rollback", cat="resilience",
                          args={"to_step": step_idx,
                                "rollback": self._rollbacks}):
            self._install(state)
            s = self._step_obj
            if getattr(s, "sentinel_state", None) is not None:
                # fresh verdict baseline — the EMA saw the fault window
                s.sentinel_state = _sentinel.init_state()
            self._consec = 0
            self._trips_seen = 0
            self.data_seed += 1
        return self.resume_step

    # -- per-step driver ------------------------------------------------------
    def after_step(self, step_idx: int, loss=None) -> str:
        """Call once per completed step. Returns ``"ok"``, ``"skip"`` (the
        in-jit gate discarded a poisoned update), ``"rollback"`` (state
        rewound — resume from :attr:`resume_step` with re-seeded data
        order), or ``"preempt"`` (priority checkpoint written — exit)."""
        del loss  # the verdict is read from device state, not the handle
        self._beat()
        if self._preempted:
            self._priority_save(step_idx)
            return "preempt"
        if self._ckpt is not None:
            self._ckpt.maybe_save(step_idx, self._capture())
        action = "ok"
        st = getattr(self._step_obj, "sentinel_state", None)
        if st is not None and (step_idx % self.check_every == 0):
            trips = int(st["trips"])
            delta = trips - self._trips_seen
            self._trips_seen = trips
            if delta > 0:
                _mstats.SENTINEL_TRIPS.add(delta)
                if _mtrace.TRACING[0]:
                    _mtrace.get_writer().add_instant(
                        "resilience.trip", time.perf_counter(),
                        cat="resilience")
                self._consec += 1
                if self._consec > self.skip_limit:
                    self.rollback()
                    return "rollback"
                action = "skip"
            else:
                self._consec = 0
        if action == "ok" and step_idx >= 0 \
                and step_idx % self.snapshot_every == 0:
            self.snapshot(step_idx)
        return action

    # -- crash auto-resume ----------------------------------------------------
    def restore_latest(self) -> Optional[int]:
        """Resume from the newest intact on-disk checkpoint (None when no
        checkpoint directory or nothing restorable). Corrupt/incomplete
        step dirs are skipped with a warning."""
        if self._ckpt is None:
            return None
        got = self._ckpt.restore_latest_tree(self._capture())
        if got is None:
            return None
        step_idx, state = got
        self._install(state)
        self.snapshot(step_idx)
        return step_idx + 1

    # -- preemption -----------------------------------------------------------
    def install_preemption_handler(self, sig: int = signal.SIGTERM) -> bool:
        """Install the SIGTERM handler (main thread only — returns False
        elsewhere). The handler just flags; the save happens at the next
        ``after_step`` on the training thread, where the device state is
        coherent."""

        def _handler(signum, frame):
            del signum, frame
            self._preempted = True

        try:
            self._prev_sigterm = signal.signal(sig, _handler)
            return True
        except ValueError:  # not the main thread
            return False

    @property
    def preempted(self) -> bool:
        return self._preempted

    def _priority_save(self, step_idx: int) -> None:
        with _mtrace.span("resilience.preempt_save", cat="resilience",
                          args={"step": step_idx}):
            if self._ckpt is not None:
                self._ckpt.save(max(step_idx, 0), self._capture())
                self._ckpt.wait_until_finished()
            else:
                self.snapshot(step_idx)
            _mstats.PREEMPT_SAVES.add()
            if self.elastic is not None:
                try:
                    from ..distributed.elastic import ElasticStatus

                    self.elastic.set_status(ElasticStatus.RESTART)
                except Exception as e:  # noqa: BLE001 — dying anyway
                    warnings.warn(f"could not mark elastic RESTART: {e}")

    # -- watchdog -------------------------------------------------------------
    def _beat(self) -> None:
        now = time.monotonic()
        with self._beat_lock:
            self._last_beat = now
        _mstats.GUARDIAN_HEARTBEAT_MS.set(int(now * 1e3))

    def _start_watchdog(self) -> None:
        if self._watchdog is not None:
            return
        self._watchdog_stop.clear()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="train-guardian-watchdog",
            daemon=True)
        self._watchdog.start()

    def _watchdog_loop(self) -> None:
        timeout = float(self.watchdog_timeout)
        poll = max(0.02, min(timeout / 4.0, 0.25))
        while not self._watchdog_stop.wait(poll):
            now = time.monotonic()
            with self._beat_lock:
                stalled = now - self._last_beat > timeout
                if stalled:
                    self._last_beat = now       # one report per stall
            if not stalled:
                continue
            _mstats.WATCHDOG_STALLS.add()
            self._dump_stall()

    def _dump_stall(self) -> None:
        """Stack dump + trace flush for a stalled step."""
        import faulthandler

        target = None
        try:
            if self.ckpt_dir is not None:
                os.makedirs(self.ckpt_dir, exist_ok=True)
                target = os.path.join(self.ckpt_dir, "watchdog_stall.txt")
                with open(target, "a") as f:
                    f.write(f"=== watchdog stall at {time.time():.3f} "
                            f"(no heartbeat for >{self.watchdog_timeout}s) "
                            f"===\n")
                    faulthandler.dump_traceback(file=f)
            else:
                faulthandler.dump_traceback(file=sys.stderr)
        except Exception:  # noqa: BLE001 — diagnostics must not kill training
            pass
        try:
            if _mtrace.TRACING[0]:
                base = self.ckpt_dir or "."
                _mtrace.get_writer().write(
                    os.path.join(base, "watchdog_trace.json"))
        except Exception:  # noqa: BLE001
            pass
        warnings.warn(
            f"watchdog: training step stalled for >{self.watchdog_timeout}s"
            + (f"; stacks dumped to {target}" if target else ""))

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=1.0)
            self._watchdog = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
