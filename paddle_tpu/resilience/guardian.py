"""TrainGuardian — snapshot / skip / rollback / preempt / watchdog.

The guardian composes the pieces that already existed in isolation
(CheckpointManager, ElasticManager, the sentinel verdict, the stat
gauges) into a training loop that survives NaNs, stalls, crashes and
preemption:

- **rolling snapshot ring**: every ``snapshot_every`` healthy steps the
  full training state (params, optimizer state, buffers, scaler, RNG,
  step count) is offloaded to HOST memory — O(model) RAM per kept
  snapshot (``keep_snapshots`` of them), no filesystem — so a rollback
  never waits on storage. ``resilience.snapshot`` trace span. With
  ``async_snapshot=True`` the interval-gated ON-DISK checkpoint writes
  move to a snapshot thread fed from an alternating two-deep buffer of
  host copies: the device->host offload is the only in-loop cost, the
  orbax serialization overlaps the following steps
  (``resilience.snapshot_async`` spans measure it), and
  ``step_async_syncs`` stays flat — the thread reads host arrays, never
  the AsyncLoss.
- **escalation ladder** on sentinel trips (read at ``check_every``
  cadence from the device-resident trip counter): the in-jit gate has
  already SKIPPED the poisoned update (GradScaler-style, params
  untouched); after ``skip_limit`` consecutive tripped steps the
  guardian REWINDS to the last snapshot (``resilience.rollback`` span,
  ``rollbacks`` gauge), multiplies the learning rate by ``lr_backoff``
  (default 1.0 = off; the replay runs gentler each rewind) and bumps
  ``data_seed`` so the caller re-seeds its data order; after
  ``max_rollbacks`` rewinds it raises :class:`TrainingAborted` — a
  babysitter would have paged a human long ago.
- **pod coordination** (``pod=PodCoordinator(...)``): the rollback step
  is AGREED pod-wide first — each host proposes the snapshot steps it
  holds through the elastic FileKVStore, the commit is the highest step
  every host holds, a laggard adopts the committed step, and an ack
  barrier aligns the replay — so every host restores the SAME step and
  the pod-wide replay stays bit-exact (see :mod:`.pod`).
- **elastic resize** (``pod=`` + ``rebuild=``): a lost host (heartbeat
  staleness, a tombstone, or an injected ``host_loss`` fault) no longer
  aborts the pod. The guardian agrees a snapshot step with the
  survivors, re-plans over the surviving device set (``rebuild`` — see
  ``fleet.auto.replan_for_devices``), reshards the agreed snapshot onto
  the new mesh through the ZeRO sharded<->unsharded checkpoint
  round-trip (full host arrays device_put under the new step's
  shardings), and resumes (``resilience.resize`` span,
  ``elastic_resizes`` gauge). :class:`TrainingAborted` is the LAST rung
  of the ladder — skip -> rollback (+LR backoff) -> resize -> abort —
  not the first.
- **preemption**: ``install_preemption_handler()`` catches SIGTERM (the
  Cloud TPU preemption notice); the next ``after_step`` forces a
  priority orbax save (``preempt_saves`` gauge), marks
  ``ElasticStatus.RESTART`` in the elastic KV store when an
  ElasticManager is attached, and returns ``"preempt"`` so the loop can
  exit cleanly. The relaunched worker auto-resumes via
  :meth:`restore_latest`.
- **watchdog**: a daemon thread watches the heartbeat gauge
  (``guardian_heartbeat_ms``, bumped by every ``after_step``); a step
  silent for ``watchdog_timeout`` seconds bumps ``watchdog_stalls``,
  dumps all thread stacks, and flushes the chrome trace for post-mortem.

Usage::

    g = TrainGuardian(step, ckpt_dir=dir, snapshot_every=20,
                      sentinel=True, watchdog_timeout=300)
    start = g.restore_latest() or 0          # crash auto-resume
    i = start
    while i < n_steps:
        loss = g.step(batch_at(i, seed=g.data_seed))
        action = g.after_step(i, loss)
        if action in ("rollback", "resize"):
            i = g.resume_step                # replay from the snapshot
            continue                         # (resize also swapped g.step)
        if action == "preempt":
            break                            # priority save already done
        i += 1
    g.close()
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
import warnings
from typing import Any, Optional

import numpy as np

from ..monitor import stats as _mstats
from ..monitor import trace as _mtrace
from . import sentinel as _sentinel

__all__ = ["TrainGuardian", "TrainingAborted"]


class TrainingAborted(RuntimeError):
    """The escalation ladder ran out: more than ``max_rollbacks`` rewinds
    (or an unrecoverable restore failure). Training must stop."""


def _host_tree(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


class TrainGuardian:
    """Wraps a train step (jit.TrainStep, DistributedTrainStep, or a
    FleetEngine) with self-healing behavior. See the module docstring for
    the ladder; all thresholds are per-instance knobs."""

    def __init__(self, step=None, ckpt_dir: Optional[str] = None,
                 snapshot_every: int = 25, skip_limit: int = 2,
                 max_rollbacks: int = 3, check_every: int = 1,
                 sentinel=True, watchdog_timeout: Optional[float] = None,
                 elastic=None, save_interval_steps: int = 1,
                 max_to_keep: int = 3, keep_snapshots: int = 1,
                 async_snapshot: bool = False, lr_backoff: float = 1.0,
                 pod=None, rebuild=None):
        self.snapshot_every = max(1, int(snapshot_every))
        self.skip_limit = int(skip_limit)
        self.max_rollbacks = int(max_rollbacks)
        self.check_every = max(1, int(check_every))
        self.sentinel_config = (_sentinel.normalize_config(sentinel)
                                if sentinel else None)
        self.watchdog_timeout = watchdog_timeout
        self.elastic = elastic
        self.pod = pod              # PodCoordinator: rollback agreement,
        #                             host-loss detection, resize devices
        if pod is not None:
            # pod-aware flight/trace dump naming: this process's dumps
            # carry the elastic layer's host id, so multi-host dumps
            # dropped into one directory merge into one timeline
            from ..monitor.flight import set_host_id

            set_host_id(pod.host)
        if watchdog_timeout and ckpt_dir is not None:
            # a watchdog-armed guardian is exactly a process whose last
            # seconds matter: arm the crash flight recorder so the stall
            # path can dump them (idempotent and process-shared)
            from ..monitor.flight import arm_flight_recorder

            arm_flight_recorder(ckpt_dir)
        self.rebuild = rebuild      # callable(devices) -> new step object
        self.keep_snapshots = max(1, int(keep_snapshots))
        self.async_snapshot = bool(async_snapshot)
        self.lr_backoff = float(lr_backoff)
        self._lr_scale = 1.0        # cumulative backoff applied so far
        self.data_seed = 0          # bumped by every rollback
        self.ckpt_dir = ckpt_dir
        self._ckpt = None
        self._ckpt_opts = (int(save_interval_steps), int(max_to_keep))
        self._obj = None            # as attached (may be a FleetEngine)
        self._step_obj = None       # the underlying train step
        self._snaps: dict = {}      # step_idx -> host state tree (ring)
        self._resizes = 0
        # async-snapshot writer state: an alternating two-deep buffer of
        # (step, host tree) pending disk serialization; the loop drops
        # the OLDEST pending entry when both buffers are in use (the
        # newest state wins — a slow filesystem thins the cadence, it
        # never stalls the step loop)
        self._snap_pending: list = []
        self._snap_cv = threading.Condition()
        self._snap_busy = False
        self._snap_thread = None
        self._snap_stop = False
        self._consec = 0            # consecutive tripped check windows
        self._trips_seen = 0
        self._rollbacks = 0
        self._preempted = False
        self._prev_sigterm = None
        # _last_beat is written by BOTH the training thread (_beat) and
        # the watchdog thread (stall re-arm) — graftlint GL003; the lock
        # also makes the read-compare-rearm in the watchdog atomic so a
        # beat landing mid-check cannot be overwritten by the re-arm
        self._beat_lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._watchdog = None
        self._watchdog_stop = threading.Event()
        self._closed = False
        if step is not None:
            self.attach(step)

    # -- attachment ---------------------------------------------------------
    @property
    def step(self):
        """The CURRENT attached step/engine — an elastic resize swaps in
        a rebuilt one, so pod-aware loops drive ``guardian.step(batch)``
        (or re-read this after a ``"resize"`` action) instead of holding
        the construction-time reference."""
        return self._obj

    def attach(self, obj) -> "TrainGuardian":
        """Bind a train step or FleetEngine; takes the initial snapshot so
        a rollback is possible from step 0."""
        self._obj = obj
        self._step_obj = getattr(obj, "train_step", obj)
        if self.ckpt_dir is not None and self._ckpt is None:
            from ..framework.checkpoint import CheckpointManager

            interval, keep = self._ckpt_opts
            self._ckpt = CheckpointManager(
                self.ckpt_dir, save_interval_steps=interval,
                max_to_keep=keep, async_save=False)
        self.snapshot(-1)
        if self.async_snapshot and self._ckpt is not None:
            self._start_snap_thread()
        if self.watchdog_timeout:
            self._start_watchdog()
        return self

    # -- state capture / install -------------------------------------------
    def _capture(self) -> dict:
        """Full training state as a pytree of arrays (host- or
        device-resident, caller's choice of offload)."""
        import jax

        from ..framework.random import get_rng_state

        s = self._step_obj
        out: dict = {"rng": jax.random.key_data(get_rng_state()),
                     # 0-d ndarray: orbax rejects numpy scalar types
                     "step_count": np.asarray(
                         getattr(s, "_step_count", 0), np.int64)}
        if hasattr(s, "params") and hasattr(s, "opt_state"):
            out["params"] = s.params
            out["opt_state"] = s.opt_state
            if getattr(s, "aux", None) is not None:
                out["aux"] = s.aux
            if getattr(s, "scaler_state", None) is not None:
                out["scaler"] = s.scaler_state
        elif hasattr(s, "_params") and hasattr(s, "_slot_values"):
            out["params"] = {k: p._data for k, p in s._params.items()}
            out["slots"] = {k: list(v) for k, v in s._slot_values.items()}
            bufs = {k: b._data for k, b in s.model.named_buffers()
                    if b is not None}
            if bufs:
                out["buffers"] = bufs
        else:
            raise TypeError(
                f"TrainGuardian cannot snapshot {type(s).__name__}: need a "
                "DistributedTrainStep-like (.params/.opt_state) or "
                "jit.TrainStep-like (._params/._slot_values) object")
        st = getattr(s, "sentinel_state", None)
        if st is not None:
            out["sentinel"] = st
        return out

    def _install(self, state: dict) -> None:
        import jax

        from ..framework.random import set_rng_state

        s = self._step_obj
        if "rng" in state:
            set_rng_state(jax.random.wrap_key_data(
                np.asarray(state["rng"]).astype(np.uint32)))
        if hasattr(s, "params") and hasattr(s, "opt_state"):
            put = lambda t, sh: (jax.device_put(t, sh) if sh is not None
                                 else jax.device_put(t))
            s.params = put(state["params"], getattr(s, "_param_sh", None))
            s.opt_state = put(state["opt_state"], getattr(s, "_opt_sh", None))
            if "aux" in state and getattr(s, "aux", None) is not None:
                s.aux = put(state["aux"], getattr(s, "_aux_sh", None))
            if "scaler" in state and getattr(s, "scaler_state", None) is not None:
                s.scaler_state = jax.device_put(state["scaler"])
        else:
            for k, arr in state["params"].items():
                s._params[k]._data = jax.device_put(np.asarray(arr))
            for k, vals in state.get("slots", {}).items():
                s._slot_values[k] = [jax.device_put(np.asarray(v))
                                     for v in vals]
                s.optimizer._set_slots(s._params[k], s._slot_values[k])
            if state.get("buffers"):
                named = {k: b for k, b in s.model.named_buffers()
                         if b is not None}
                for k, arr in state["buffers"].items():
                    named[k]._data = jax.device_put(np.asarray(arr))
        if "step_count" in state and hasattr(s, "_step_count"):
            s._step_count = int(state["step_count"])
        if "sentinel" in state and getattr(s, "sentinel_state", None) is not None:
            s.sentinel_state = jax.device_put(
                jax.tree_util.tree_map(np.asarray, state["sentinel"]))
        # FleetEngine: mirror the restored device params back into the
        # eager Layer so state_dict/save readers stay consistent
        if self._obj is not self._step_obj:
            eng = self._obj
            if hasattr(eng, "_write_back"):
                eng._write_back(self._step_obj.params)
            if hasattr(eng, "_write_back_buffers"):
                eng._write_back_buffers(getattr(self._step_obj, "aux", None))

    # -- snapshot / rollback -------------------------------------------------
    def _span_args(self, **kw) -> dict:
        if self.pod is not None:
            kw["host"] = self.pod.host
        return kw

    def snapshot(self, step_idx: int) -> None:
        """Host-offloaded rolling snapshot into the ring (keeps the
        newest ``keep_snapshots``). The device->host copy happens here,
        on the loop thread — the arrays it captures are donated to the
        very next step, so offloading later would read freed buffers."""
        with _mtrace.span("resilience.snapshot", cat="resilience",
                          args=self._span_args(step=step_idx)):
            self._snaps[int(step_idx)] = _host_tree(self._capture())
            for old in sorted(self._snaps)[:-self.keep_snapshots]:
                del self._snaps[old]

    @property
    def resume_step(self) -> int:
        """First step index to (re)run after a rollback/restore."""
        return (max(self._snaps) + 1) if self._snaps else 0

    def rollback(self) -> int:
        """Rewind to the last snapshot — pod-AGREED when a coordinator is
        attached, so every host restores the same step. Returns the step
        index to resume from; raises :class:`TrainingAborted` past
        ``max_rollbacks``."""
        if not self._snaps:
            raise TrainingAborted("sentinel tripped but no snapshot exists")
        self._rollbacks += 1
        _mstats.ROLLBACKS.add()
        if self._rollbacks > self.max_rollbacks:
            raise TrainingAborted(
                f"aborting: {self._rollbacks} rollbacks exceed "
                f"max_rollbacks={self.max_rollbacks}")
        step_idx = self._agree_step()
        state = self._snaps[step_idx]
        with _mtrace.span("resilience.rollback", cat="resilience",
                          args=self._span_args(to_step=step_idx,
                                               rollback=self._rollbacks)):
            self._install(state)
            self._discard_after(step_idx)
            s = self._step_obj
            if getattr(s, "sentinel_state", None) is not None:
                # fresh verdict baseline — the EMA saw the fault window
                s.sentinel_state = _sentinel.init_state()
            self._consec = 0
            self._trips_seen = 0
            self.data_seed += 1
            self._backoff_lr()
        return self.resume_step

    def _agree_step(self, expected=None) -> int:
        """The snapshot step to restore: pod-committed when coordinated
        (a laggard host adopts the commit even when it is older than its
        own newest snapshot), else simply the newest held."""
        if self.pod is None:
            return max(self._snaps)
        from .pod import PodAgreementError

        try:
            step_idx = self.pod.agree_rollback(sorted(self._snaps),
                                               expected=expected)
        except PodAgreementError as e:
            raise TrainingAborted(f"pod rollback agreement failed: {e}") \
                from e
        if step_idx not in self._snaps:
            # the protocol commits a COMMON step, so this is a local
            # bookkeeping bug or a snapshot dropped mid-agreement
            raise TrainingAborted(
                f"pod committed step {step_idx} but this host holds "
                f"{sorted(self._snaps)}")
        return step_idx

    def _discard_after(self, step_idx: int) -> None:
        """Drop ring snapshots NEWER than the restored step — they were
        taken on the poisoned timeline the pod just agreed to abandon."""
        for s in [s for s in self._snaps if s > step_idx]:
            del self._snaps[s]

    def _backoff_lr(self) -> None:
        """Apply the post-rollback LR backoff (``lr_backoff=1.0``
        disables — the replay stays bit-exact vs a fault-free run)."""
        if self.lr_backoff == 1.0:
            return
        self._lr_scale *= self.lr_backoff
        s = self._step_obj
        if hasattr(s, "scale_lr"):
            s.scale_lr(self._lr_scale)
        else:
            warnings.warn(
                f"lr_backoff={self.lr_backoff} set but "
                f"{type(s).__name__} has no scale_lr(); learning rate "
                "left unchanged")

    # -- per-step driver ------------------------------------------------------
    def after_step(self, step_idx: int, loss=None) -> str:
        """Call once per completed step. Returns ``"ok"``, ``"skip"`` (the
        in-jit gate discarded a poisoned update), ``"rollback"`` (state
        rewound — resume from :attr:`resume_step` with re-seeded data
        order), ``"resize"`` (a host was lost; the pod re-planned over the
        survivors, resharded the agreed snapshot and swapped in the
        rebuilt step — resume from :attr:`resume_step`), or ``"preempt"``
        (priority checkpoint written — exit)."""
        del loss  # the verdict is read from device state, not the handle
        self._beat()
        if self._preempted:
            self._priority_save(step_idx)
            return "preempt"
        if self._ckpt is not None:
            if self.async_snapshot:
                self._enqueue_disk_save(step_idx)
            else:
                self._ckpt.maybe_save(step_idx, self._capture())
        action = "ok"
        st = getattr(self._step_obj, "sentinel_state", None)
        if st is not None and (step_idx % self.check_every == 0):
            trips = int(st["trips"])
            delta = trips - self._trips_seen
            self._trips_seen = trips
            if delta > 0:
                _mstats.SENTINEL_TRIPS.add(delta)
                if _mtrace.TRACING[0]:
                    _mtrace.get_writer().add_instant(
                        "resilience.trip", time.perf_counter(),
                        cat="resilience")
                self._consec += 1
                if self._consec > self.skip_limit:
                    self.rollback()
                    return "rollback"
                action = "skip"
            else:
                self._consec = 0
        if self.pod is not None and step_idx % self.check_every == 0:
            self.pod.maybe_heartbeat()
            lost = self.pod.lost_hosts(step_idx)
            if lost:
                self.resize(lost)
                return "resize"
        if action == "ok" and step_idx >= 0 \
                and step_idx % self.snapshot_every == 0:
            self.snapshot(step_idx)
        return action

    # -- elastic resize -------------------------------------------------------
    def resize(self, lost) -> int:
        """Host loss -> replan + reshard + resume instead of aborting.

        The survivors agree the snapshot step to restore, ``rebuild``
        re-plans over the surviving device set (typically
        ``fleet.auto.replan_for_devices`` + a fresh DistributedTrainStep
        on the new mesh), and the agreed snapshot — full unsharded host
        arrays, exactly what the ZeRO-2/3 checkpoint round-trip emits —
        is device_put under the NEW step's shardings. Returns the step
        index to resume from; :class:`TrainingAborted` only when no
        rebuild hook exists, no snapshot is restorable, or the rebuild
        itself fails (e.g. fleet.auto finds no plan that fits N-k
        hosts) — the LAST rung of the ladder."""
        if self.rebuild is None:
            raise TrainingAborted(
                f"host(s) {sorted(lost)} lost and no rebuild= hook is "
                "attached — cannot resize, aborting")
        if not self._snaps:
            raise TrainingAborted(
                f"host(s) {sorted(lost)} lost before any snapshot exists")
        self.drain_snapshots()
        survivors = [h for h in (self.pod.hosts if self.pod else [])
                     if h not in set(lost)]
        step_idx = self._agree_step(expected=survivors or None)
        devices = (self.pod.surviving_devices(lost)
                   if self.pod is not None else None)
        with _mtrace.span("resilience.resize", cat="resilience",
                          args=self._span_args(
                              step=step_idx, lost=sorted(lost),
                              devices=len(devices or []))):
            if self.pod is not None:
                self.pod.remove_hosts(lost)
            try:
                new_step = self.rebuild(devices)
            except Exception as e:  # noqa: BLE001 — planner no-fit etc.
                raise TrainingAborted(
                    f"resize rebuild over {len(devices or [])} surviving "
                    f"device(s) failed: {type(e).__name__}: {e}") from e
            self._adopt_step(new_step)
            self._install(self._snaps[step_idx])
            self._discard_after(step_idx)
            s = self._step_obj
            if getattr(s, "sentinel_state", None) is not None:
                s.sentinel_state = _sentinel.init_state()
            self._consec = 0
            self._trips_seen = 0
            self._resizes += 1
            _mstats.ELASTIC_RESIZES.add()
        return self.resume_step

    def _adopt_step(self, new_step) -> None:
        """Swap in the rebuilt train step. A FleetEngine attachment keeps
        the engine as the façade and hands it the new inner step (eager
        mirrors refresh on the next write-back)."""
        if self._obj is not self._step_obj \
                and hasattr(self._obj, "adopt_train_step"):
            self._obj.adopt_train_step(
                getattr(new_step, "train_step", new_step))
            self._step_obj = self._obj.train_step
        else:
            self._obj = new_step
            self._step_obj = getattr(new_step, "train_step", new_step)
        if self._lr_scale != 1.0 and hasattr(self._step_obj, "scale_lr"):
            self._step_obj.scale_lr(self._lr_scale)

    # -- async snapshot writer -----------------------------------------------
    def _enqueue_disk_save(self, step_idx: int) -> None:
        """Hand the interval-gated on-disk save to the snapshot thread.
        The host offload happens HERE, on the loop thread — the captured
        device arrays are donated to the very next step, so the thread
        must only ever see host copies."""
        if not self._ckpt.should_save(step_idx):
            return
        state = _host_tree(self._capture())
        with self._snap_cv:
            if len(self._snap_pending) >= 2:
                # both buffers in use: the filesystem is slower than the
                # save cadence — keep the newest state, thin the cadence
                self._snap_pending.pop(0)
            self._snap_pending.append((int(step_idx), state))
            self._snap_cv.notify_all()

    def _snap_loop(self) -> None:
        while True:
            with self._snap_cv:
                while not self._snap_pending and not self._snap_stop:
                    self._snap_cv.wait(0.1)
                if self._snap_stop and not self._snap_pending:
                    return
                step_idx, state = self._snap_pending.pop(0)
                self._snap_busy = True
            try:
                with _mtrace.span("resilience.snapshot_async",
                                  cat="resilience",
                                  args=self._span_args(step=step_idx)):
                    # already interval-gated on the loop thread
                    self._ckpt.save(step_idx, state)
            except Exception as e:  # noqa: BLE001 — a failed background
                # save must not kill training; the next cadence retries
                warnings.warn(f"async checkpoint save at step {step_idx} "
                              f"failed: {type(e).__name__}: {e}")
            finally:
                with self._snap_cv:
                    self._snap_busy = False
                    self._snap_cv.notify_all()

    def _start_snap_thread(self) -> None:
        if self._snap_thread is not None:
            return
        self._snap_stop = False
        self._snap_thread = threading.Thread(
            target=self._snap_loop, name="train-guardian-snapshot",
            daemon=True)
        self._snap_thread.start()

    def drain_snapshots(self, timeout: float = 60.0) -> None:
        """Block until the snapshot thread has no pending/in-flight disk
        writes (rollback, resize, restore and shutdown all wait here —
        state decisions must not race a half-written checkpoint)."""
        if self._snap_thread is None:
            return
        deadline = time.monotonic() + timeout
        with self._snap_cv:
            while self._snap_pending or self._snap_busy:
                if not self._snap_cv.wait(0.05) \
                        and time.monotonic() > deadline:
                    warnings.warn("drain_snapshots timed out with a "
                                  "disk write still in flight")
                    return

    # -- crash auto-resume ----------------------------------------------------
    def restore_latest(self) -> Optional[int]:
        """Resume from the newest intact on-disk checkpoint (None when no
        checkpoint directory or nothing restorable). Corrupt/incomplete
        step dirs are skipped with a warning."""
        if self._ckpt is None:
            return None
        self.drain_snapshots()
        got = self._ckpt.restore_latest_tree(self._capture())
        if got is None:
            return None
        step_idx, state = got
        self._install(state)
        self.snapshot(step_idx)
        return step_idx + 1

    # -- preemption -----------------------------------------------------------
    def install_preemption_handler(self, sig: int = signal.SIGTERM) -> bool:
        """Install the SIGTERM handler (main thread only — returns False
        elsewhere). The handler just flags; the save happens at the next
        ``after_step`` on the training thread, where the device state is
        coherent."""

        def _handler(signum, frame):
            del signum, frame
            self._preempted = True

        try:
            self._prev_sigterm = signal.signal(sig, _handler)
            return True
        except ValueError:  # not the main thread
            return False

    @property
    def preempted(self) -> bool:
        return self._preempted

    def _priority_save(self, step_idx: int) -> None:
        self.drain_snapshots()
        with _mtrace.span("resilience.preempt_save", cat="resilience",
                          args=self._span_args(step=step_idx)):
            if self._ckpt is not None:
                self._ckpt.save(max(step_idx, 0), self._capture())
                self._ckpt.wait_until_finished()
            else:
                self.snapshot(step_idx)
            _mstats.PREEMPT_SAVES.add()
            if self.elastic is not None:
                try:
                    from ..distributed.elastic import ElasticStatus

                    self.elastic.set_status(ElasticStatus.RESTART)
                except Exception as e:  # noqa: BLE001 — dying anyway
                    warnings.warn(f"could not mark elastic RESTART: {e}")

    # -- watchdog -------------------------------------------------------------
    def _beat(self) -> None:
        now = time.monotonic()
        with self._beat_lock:
            self._last_beat = now
        _mstats.GUARDIAN_HEARTBEAT_MS.set(int(now * 1e3))

    def _start_watchdog(self) -> None:
        if self._watchdog is not None:
            return
        self._watchdog_stop.clear()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="train-guardian-watchdog",
            daemon=True)
        self._watchdog.start()

    def _watchdog_loop(self) -> None:
        timeout = float(self.watchdog_timeout)
        poll = max(0.02, min(timeout / 4.0, 0.25))
        while not self._watchdog_stop.wait(poll):
            now = time.monotonic()
            with self._beat_lock:
                stalled = now - self._last_beat > timeout
                if stalled:
                    self._last_beat = now       # one report per stall
            if not stalled:
                continue
            _mstats.WATCHDOG_STALLS.add()
            self._dump_stall()

    def _dump_stall(self) -> None:
        """Stack dump + trace flush for a stalled step."""
        import faulthandler

        target = None
        try:
            if self.ckpt_dir is not None:
                os.makedirs(self.ckpt_dir, exist_ok=True)
                target = os.path.join(self.ckpt_dir, "watchdog_stall.txt")
                with open(target, "a") as f:
                    f.write(f"=== watchdog stall at {time.time():.3f} "
                            f"(no heartbeat for >{self.watchdog_timeout}s) "
                            f"===\n")
                    faulthandler.dump_traceback(file=f)
            else:
                faulthandler.dump_traceback(file=sys.stderr)
        except Exception:  # noqa: BLE001 — diagnostics must not kill training
            pass
        try:
            if _mtrace.TRACING[0]:
                base = self.ckpt_dir or "."
                _mtrace.get_writer().write(
                    os.path.join(base, "watchdog_trace.json"))
        except Exception:  # noqa: BLE001
            pass
        # flight-recorder dump (ISSUE 15): the bounded ring of recent
        # spans/gauge deltas — works even when full tracing is off, and
        # never raises (the stall is the story, not the dump)
        from ..monitor.flight import dump_flight

        dump_flight("guardian_watchdog_stall",
                    trace_dir=self.ckpt_dir,
                    extra={"watchdog_timeout": self.watchdog_timeout})
        warnings.warn(
            f"watchdog: training step stalled for >{self.watchdog_timeout}s"
            + (f"; stacks dumped to {target}" if target else ""))

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._snap_thread is not None:
            self.drain_snapshots()
            with self._snap_cv:
                self._snap_stop = True
                self._snap_cv.notify_all()
            self._snap_thread.join(timeout=5.0)
            self._snap_thread = None
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=1.0)
            self._watchdog = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
