"""Deterministic fault injection (FLAGS_fault_inject).

Production failure modes — NaN gradients, process crashes, TPU-slice
preemption, flaky checkpoint filesystems, stalled input pipelines — are
rare in CI and constant in the field. This registry makes each of them a
one-flag reproduction so the failure path is exercised as routinely as
the hot path (Orca/vLLM-style engineering; the reference ships the same
spirit as FLAGS_check_nan_inf + elastic relaunch tests).

Spec grammar (comma/semicolon-separated)::

    FLAGS_fault_inject="nan_grad@step=50:repeat=3,crash@step=120"
    FLAGS_fault_inject="ckpt_io_error@p=0.5:seed=7:repeat=4"
    FLAGS_fault_inject="stall@step=80:secs=2,preempt@step=200"
    FLAGS_fault_inject="host_loss@step=40:host=h2,kv_partition@step=10:secs=0.5"

Each fault is ``kind@trigger[:opt=value]*`` where trigger is either
``step=N`` (fires on the first ``repeat`` step-encounters with index >=
N — consecutive steps, and NOT again after the budget is spent, so a
rollback replay of the same step indices runs clean) or ``p=F`` (fires
per encounter with probability F from a private ``seed``-ed RNG —
deterministic across runs). Options: ``repeat`` (default 1 for step
faults, unlimited for p faults), ``secs`` (stall duration), ``seed``,
``host`` (which simulated host a pod fault hits), ``replica`` (which
EngineRouter replica a serving tick fault hits).

Serving chaos (ISSUE 13) adds three kinds whose "step" counts something
other than a train step: ``replica_crash`` / ``slow_tick`` fire on an
engine's SCHEDULER TICK index (per replica), ``conn_drop`` on the front
end's streaming-connection index::

    FLAGS_fault_inject="replica_crash@step=30:replica=0,slow_tick@step=5:secs=0.2:repeat=3"
    FLAGS_fault_inject="conn_drop@step=2"

Fleet network chaos (ISSUE 20) adds four kinds keyed by the RPC CALL
index — each :class:`~paddle_tpu.serving.rpc.RpcClient` owns a private
per-peer call counter (bumped only while faults are armed, so flag-unset
stays bit-identical), and the client hook claims these kinds against it.
``rpc_drop`` kills the socket before the request frame leaves (a
mid-call transport death — the retry/breaker path), ``rpc_delay`` makes
the RECEIVER sleep ``secs`` before dispatch (the deadline-shed path),
``rpc_corrupt`` flips a byte inside the frame (blob when the call
carries a crc, JSON header otherwise — crc/torn-frame paths fire), and
``net_partition`` opens a both-directions block between two host groups
(``hosts=A|B``; group members joined with ``+``) for ``secs``, consulted
by every client before dialing::

    FLAGS_fault_inject="rpc_drop@call=3:method=export_range:host=h0:repeat=99"
    FLAGS_fault_inject="rpc_delay@call=1:secs=0.5,rpc_corrupt@call=2"
    FLAGS_fault_inject="net_partition@step=0:secs=1:hosts=router|h2"

Lifecycle chaos (ISSUE 14) adds two kinds keyed by the
:class:`~paddle_tpu.serving.lifecycle.ReplicaSupervisor`'s OWN
``restart=`` index spaces (spawn attempts / rejoins — never a train
step, so training fault replay stays clean): ``spawn_fail`` makes the
engine factory raise on the Nth respawn (exercising the
backoff→quarantine ladder), ``replica_flap`` crashes a replica at its
next busy scheduler tick after each of ``times`` rejoins starting at
the Nth (the flapping replica that drives the quarantine rung)::

    FLAGS_fault_inject="spawn_fail@restart=1:times=2"
    FLAGS_fault_inject="replica_flap@restart=1:times=3"

Kinds and their hook points:

=============  ==========================================  ===============
kind           effect                                      hook point
=============  ==========================================  ===============
nan_grad       float leaves of the batch become NaN        train steps
crash          raises :class:`InjectedCrash`               train steps
preempt        ``signal.raise_signal(SIGTERM)``            train steps
stall          ``time.sleep(secs)`` inside the step        train steps
host_loss      tombstones ``host`` in the elastic KV       resilience/pod.py
               store (the pod sees a dead host and
               escalates to elastic resize)
kv_partition   FileKVStore raises OSError for ``secs``     resilience/pod.py +
               (a transient shared-filesystem partition;   distributed/elastic.py
               heartbeats ride the put retry budget)
serving_nan    NaNs one slot's KV rows at the first        serving/engine.py
               decode tick of request id >= N (keyed by
               REQUEST id, not train step)
replica_crash  serving scheduler raises InjectedCrash at   serving/engine.py
               engine tick N (``replica=R`` limits it to
               one EngineRouter replica; keyed by TICK,
               its own index space per replica)
slow_tick      ``time.sleep(secs)`` in the scheduler loop  serving/engine.py
               at tick >= N (``repeat=K`` consecutive
               ticks; drives the brownout EWMA and the
               watchdog latency rung)
conn_drop      the SSE client "vanishes" mid-stream: the   serving/frontend.py
               front end aborts connection index >= N
               after its first piece (exercises the
               disconnect-cancel block-release path);
               bench chaos consumers claim the same spec
spawn_fail     the supervisor's engine factory raises      serving/lifecycle.py
               InjectedCrash on spawn attempt >= N
               (``times=K`` attempts; keyed by the
               supervisor's RESTART index, its own space)
replica_flap   the freshly-rejoined replica crashes at     serving/lifecycle.py
               its next busy tick after rejoin index >= N
               (``times=K`` rejoins; the flapping-replica
               chaos that drives the quarantine ladder)
input_stall    ``time.sleep(secs)`` in the prefetcher      io/prefetch.py
ckpt_io_error  raises ``OSError`` during checkpoint save   framework/checkpoint.py
rpc_drop       client socket dies before the frame is      serving/rpc.py
               sent (transport death; keyed by the
               client's per-peer CALL index; ``method=``
               / ``host=`` filter which calls qualify)
rpc_delay      receiver sleeps ``secs`` before dispatch    serving/rpc.py
               (drives the frame-header deadline shed)
rpc_corrupt    one byte of the frame is flipped in         serving/rpc.py
               flight (blob if the call carries a crc,
               JSON header otherwise)
net_partition  both directions blocked between host        serving/rpc.py
               groups ``hosts=A|B`` for ``secs``
               (members joined with ``+``)
=============  ==========================================  ===============

Train-step hooks live in ``parallel/train_step.py``,
``distributed/fleet/engine.py`` and ``jit.TrainStep``; the registry
evaluates each step index ONCE and hands each fired fault to the first
hook that claims it, so the fleet engine wrapping a DistributedTrainStep
does not double-fire.

Cost when idle: every hook site guards on ``ENABLED[0]`` (one list
index), and with the flag unset no batch is touched — training is
bit-for-bit identical to a build without this module.
"""
from __future__ import annotations

import random as _random
import signal
import time
from typing import Dict, List, Optional

import numpy as np

from ..core import native as _native
from ..monitor import stats as _mstats

__all__ = ["FaultSpec", "FaultRegistry", "InjectedCrash", "FAULTS",
           "ENABLED", "configure_faults", "begin_kv_partition",
           "kv_partition_active", "begin_net_partition",
           "net_partition_active", "net_partition_blocks"]

# fast-path gate: hook sites read ENABLED[0] before touching the registry
ENABLED = [False]

_STEP_KINDS = ("nan_grad", "crash", "preempt", "stall", "host_loss",
               "kv_partition")
# request-id-keyed kinds live in their OWN index space: a serving request
# id must never consume a step-keyed budget (or vice versa) when training
# and serving share a process
_RID_KINDS = ("serving_nan",)
# serving-TICK-keyed kinds (per engine replica) and the connection-index
# kind — each evaluated at a single hook site, so per-spec budgets
# suffice (no claimed-once index bookkeeping needed)
_TICK_KINDS = ("replica_crash", "slow_tick")
_CONN_KINDS = ("conn_drop",)
# supervisor-RESTART-keyed kinds (serving/lifecycle.py): spawn_fail fires
# on the supervisor's spawn-attempt index, replica_flap on its rejoin
# index — both counters the supervisor owns, so lifecycle chaos never
# consumes a train-step budget and rollback replay stays clean
_RESTART_KINDS = ("spawn_fail", "replica_flap")
# RPC-CALL-keyed kinds (serving/rpc.py): each RpcClient's private
# per-peer call counter is the index space, so network chaos never
# consumes a train-step/tick/restart budget and replays stay clean
_RPC_KINDS = ("rpc_drop", "rpc_delay", "rpc_corrupt")
# net_partition ALSO fires off a client call index (``step=N`` counts
# RPC calls here, like conn_drop's "step" counts connections), but its
# effect is a module-level window every client consults
_NET_KINDS = ("net_partition",)

# monotonic deadline of the currently-injected KV-store partition window
# (0.0 = none). FileKVStore consults kv_partition_active() on every op.
_PARTITION_UNTIL = [0.0]

# injected NETWORK partition: [deadline, (frozenset_a, frozenset_b)].
# RpcClient consults net_partition_blocks(local, peer) before dialing —
# a synchronous RPC blocked at the caller blocks both directions.
_NET_PARTITION = [0.0, ()]


def begin_kv_partition(secs: float) -> None:
    """Open an injected shared-filesystem partition window: every
    FileKVStore op raises OSError until it closes."""
    _PARTITION_UNTIL[0] = time.monotonic() + float(secs)


def kv_partition_active() -> bool:
    return ENABLED[0] and time.monotonic() < _PARTITION_UNTIL[0]


def begin_net_partition(secs: float, groups) -> None:
    """Open an injected network partition window between two host
    groups: every RPC between a host in one group and a host in the
    other fails fast (both directions) until it closes."""
    _NET_PARTITION[1] = tuple(frozenset(str(h) for h in g) for g in groups)
    _NET_PARTITION[0] = time.monotonic() + float(secs)


def net_partition_active() -> bool:
    return ENABLED[0] and time.monotonic() < _NET_PARTITION[0]


def net_partition_blocks(a: str, b: str) -> bool:
    """True when hosts ``a`` and ``b`` sit on opposite sides of the
    currently-open injected partition."""
    if not net_partition_active() or len(_NET_PARTITION[1]) != 2:
        return False
    ga, gb = _NET_PARTITION[1]
    return (a in ga and b in gb) or (a in gb and b in ga)


class InjectedCrash(RuntimeError):
    """Raised by a ``crash@step=N`` fault — stands in for a worker dying
    mid-step (segfault, OOM-kill, device wedging)."""


class FaultSpec:
    """One parsed fault clause."""

    __slots__ = ("kind", "step", "p", "restart", "call", "repeat", "secs",
                 "seed", "host", "replica", "method", "hosts", "remaining",
                 "_rng")

    def __init__(self, kind: str, step: Optional[int] = None,
                 p: Optional[float] = None, repeat: Optional[int] = None,
                 secs: float = 1.0, seed: int = 0,
                 host: Optional[str] = None,
                 replica: Optional[int] = None,
                 restart: Optional[int] = None,
                 call: Optional[int] = None,
                 method: Optional[str] = None,
                 hosts: Optional[str] = None):
        triggers = sum(t is not None for t in (step, p, restart, call))
        if triggers != 1:
            raise ValueError(
                f"fault {kind!r} needs exactly one trigger: step=N, p=F, "
                "restart=N or call=N")
        if restart is not None and kind not in _RESTART_KINDS:
            raise ValueError(
                f"restart= only triggers lifecycle kinds {_RESTART_KINDS}, "
                f"not {kind!r}")
        if kind in _RESTART_KINDS and restart is None:
            raise ValueError(f"{kind} needs restart=N (which supervisor "
                             "spawn/rejoin index fires it)")
        if call is not None and kind not in _RPC_KINDS:
            raise ValueError(
                f"call= only triggers rpc kinds {_RPC_KINDS}, not {kind!r}")
        if kind in _RPC_KINDS and call is None:
            raise ValueError(f"{kind} needs call=N (which per-peer RPC "
                             "call index fires it)")
        if kind == "host_loss" and not host:
            raise ValueError("host_loss needs host=H (which simulated host "
                             "dies)")
        if kind in _NET_KINDS:
            if step is None:
                raise ValueError("net_partition needs step=N (the RPC call "
                                 "index that opens the window)")
            if not hosts or "|" not in str(hosts):
                raise ValueError("net_partition needs hosts=A|B (two host "
                                 "groups; members joined with '+')")
        elif hosts is not None:
            raise ValueError(f"hosts= only applies to net_partition, "
                             f"not {kind!r}")
        self.kind = kind
        self.step = step
        self.p = p
        self.restart = None if restart is None else int(restart)
        self.call = None if call is None else int(call)
        self.host = host
        self.method = method
        self.hosts = None if hosts is None else tuple(
            frozenset(h for h in g.split("+") if h)
            for g in str(hosts).split("|"))
        self.replica = None if replica is None else int(replica)
        # step faults default to firing once; p faults to unlimited
        self.repeat = repeat if repeat is not None else (1 if p is None
                                                        else -1)
        self.secs = float(secs)
        self.seed = int(seed)
        self.remaining = self.repeat
        self._rng = _random.Random(self.seed)

    def spent(self) -> bool:
        return self.remaining == 0

    def consume(self) -> None:
        if self.remaining > 0:
            self.remaining -= 1

    def __repr__(self):
        if self.step is not None:
            trig = f"step={self.step}"
        elif self.restart is not None:
            trig = f"restart={self.restart}"
        elif self.call is not None:
            trig = f"call={self.call}"
        else:
            trig = f"p={self.p}"
        return (f"FaultSpec({self.kind}@{trig}, repeat={self.repeat}, "
                f"remaining={self.remaining})")


def parse_spec(text: str) -> List[FaultSpec]:
    """Parse a FLAGS_fault_inject value into FaultSpecs (empty for '')."""
    out: List[FaultSpec] = []
    for clause in text.replace(";", ",").split(","):
        clause = clause.strip()
        if not clause:
            continue
        if "@" not in clause:
            raise ValueError(f"bad fault clause {clause!r} (need kind@trigger)")
        kind, rest = clause.split("@", 1)
        kw: Dict[str, str] = {}
        for part in rest.split(":"):
            if "=" not in part:
                raise ValueError(f"bad fault option {part!r} in {clause!r}")
            k, v = part.split("=", 1)
            kw[k.strip()] = v.strip()
        if "times" in kw:       # lifecycle-spec alias: times=K == repeat=K
            kw.setdefault("repeat", kw.pop("times"))
        out.append(FaultSpec(
            kind.strip(),
            step=int(kw["step"]) if "step" in kw else None,
            p=float(kw["p"]) if "p" in kw else None,
            repeat=int(kw["repeat"]) if "repeat" in kw else None,
            secs=float(kw.get("secs", 1.0)),
            seed=int(kw.get("seed", 0)),
            host=kw.get("host"),
            replica=int(kw["replica"]) if "replica" in kw else None,
            restart=int(kw["restart"]) if "restart" in kw else None,
            call=int(kw["call"]) if "call" in kw else None,
            method=kw.get("method"), hosts=kw.get("hosts")))
    return out


def _corrupt_batch(batch):
    """NaN the float leaves of a batch pytree (lists/tuples/dicts of
    Tensors / numpy / jax arrays). Integer leaves are untouched — NaN has
    no integer encoding — so nan_grad needs at least one float input."""
    from ..framework.core import Tensor

    def walk(x):
        if isinstance(x, (list, tuple)):
            return type(x)(walk(v) for v in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, Tensor):
            return Tensor(walk(x._data), stop_gradient=x.stop_gradient,
                          name=x.name)
        dt = getattr(x, "dtype", None)
        if dt is not None and np.issubdtype(np.dtype(dt), np.floating):
            return x * float("nan")
        return x

    return walk(batch)


class FaultRegistry:
    """Holds the configured faults and evaluates them at the hook points.

    Step-keyed faults are evaluated once per step INDEX (the first hook
    to see a new index computes which faults fire; re-asking for the same
    index — e.g. FleetEngine.step delegating to DistributedTrainStep —
    hands each fired fault out only once). A step index revisited after a
    rollback is re-evaluated, so a fault with budget left fires again and
    an exhausted one stays quiet.
    """

    def __init__(self):
        self.faults: List[FaultSpec] = []
        self._cur_step: Optional[int] = None
        self._cur_fired: Dict[str, FaultSpec] = {}
        self._cur_rid: Optional[int] = None
        self._rid_fired: Dict[str, FaultSpec] = {}

    # -- configuration ------------------------------------------------------
    def configure(self, text: str) -> None:
        if str(text).strip().lower() in ("", "0", "false", "none", "off"):
            text = ""
        self.faults = parse_spec(text or "")
        self._cur_step = None
        self._cur_fired = {}
        self._cur_rid = None
        self._rid_fired = {}
        _PARTITION_UNTIL[0] = 0.0
        _NET_PARTITION[0] = 0.0
        _NET_PARTITION[1] = ()
        ENABLED[0] = bool(self.faults)

    # -- evaluation ---------------------------------------------------------
    def _fires(self, f: FaultSpec, step: Optional[int]) -> bool:
        if f.spent():
            return False
        if f.step is not None:
            return step is not None and step >= f.step
        return f._rng.random() < f.p

    def _eval_step(self, step: int) -> None:
        if step == self._cur_step:
            return
        self._cur_step = step
        self._cur_fired = {}
        for f in self.faults:
            if f.kind in _STEP_KINDS and f.step is not None \
                    and self._fires(f, step):
                f.consume()
                self._cur_fired[f.kind] = f

    def take(self, kind: str, step: int) -> Optional[FaultSpec]:
        """Claim a step-keyed fault for this step index (None = not
        firing, or already claimed by an outer hook)."""
        self._eval_step(step)
        return self._cur_fired.pop(kind, None)

    def take_request(self, kind: str, rid: int) -> Optional[FaultSpec]:
        """Claim a REQUEST-id-keyed fault (serving hooks). Request ids
        live in their own index space so a serving fault never consumes a
        train-step budget and vice versa."""
        if rid != self._cur_rid:
            self._cur_rid = rid
            self._rid_fired = {}
            for f in self.faults:
                if f.kind in _RID_KINDS and f.step is not None \
                        and self._fires(f, rid):
                    f.consume()
                    self._rid_fired[f.kind] = f
        return self._rid_fired.pop(kind, None)

    def take_tick(self, kind: str, replica: Optional[int],
                  tick: int) -> Optional[FaultSpec]:
        """Claim a serving-TICK-keyed fault (replica_crash / slow_tick)
        for one engine replica's scheduler loop. Ticks live in their own
        per-replica index space; ``replica=R`` in the spec limits the
        fault to the EngineRouter replica with that id (None in the
        spec = any replica, first to reach the tick claims it)."""
        for f in self.faults:
            if f.kind != kind or f.kind not in _TICK_KINDS or f.spent() \
                    or f.step is None:
                continue
            if f.replica is not None and (replica is None
                                          or int(replica) != f.replica):
                continue
            if tick >= f.step:
                f.consume()
                return f
        return None

    def take_restart(self, kind: str, index: int) -> Optional[FaultSpec]:
        """Claim a supervisor-RESTART-keyed fault (spawn_fail /
        replica_flap) for one ReplicaSupervisor spawn-attempt or rejoin
        index — the supervisor owns both counters, so these budgets are
        untouchable from train-step or serving-tick hooks."""
        for f in self.faults:
            if f.kind != kind or f.kind not in _RESTART_KINDS \
                    or f.spent() or f.restart is None:
                continue
            if index >= f.restart:
                f.consume()
                return f
        return None

    def take_rpc(self, host: str, method: str, index: int
                 ) -> Dict[str, FaultSpec]:
        """Claim every RPC-call-keyed fault due at one client call.

        ``index`` is the calling RpcClient's private per-peer call
        counter — its own index space, so network chaos never consumes a
        step/tick/restart budget. ``host``/``method`` filters in the
        spec (``host=H`` = the PEER host, ``method=M``) restrict which
        calls a clause can claim. A due ``net_partition`` is consumed
        here too: it opens the module-level window
        (:func:`net_partition_blocks`) rather than riding the returned
        dict."""
        fired: Dict[str, FaultSpec] = {}
        for f in self.faults:
            if f.spent():
                continue
            if f.kind in _RPC_KINDS:
                if f.host is not None and f.host != host:
                    continue
                if f.method is not None and f.method != method:
                    continue
                if index >= f.call and f.kind not in fired:
                    f.consume()
                    fired[f.kind] = f
            elif f.kind in _NET_KINDS and f.step is not None \
                    and index >= f.step:
                f.consume()
                begin_net_partition(f.secs, f.hosts)
        return fired

    def take_conn(self, index: int) -> Optional[FaultSpec]:
        """Claim a connection-indexed fault (conn_drop) for the front
        end's Nth streaming response (its own index space)."""
        for f in self.faults:
            if f.kind not in _CONN_KINDS or f.spent():
                continue
            if (f.step is not None and index >= f.step) or \
                    (f.p is not None and f._rng.random() < f.p):
                f.consume()
                return f
        return None

    def chance(self, kind: str) -> Optional[FaultSpec]:
        """Per-encounter (p=...) fault draw."""
        for f in self.faults:
            if f.kind == kind and f.p is not None and self._fires(f, None):
                f.consume()
                return f
        return None

    # -- hook points --------------------------------------------------------
    def on_train_step(self, step: int, batch):
        """The train-step hook: crash / stall / preempt / nan_grad, in
        that order. Returns the (possibly corrupted) batch."""
        f = self.take("crash", step)
        if f is not None:
            _mstats.FAULTS_INJECTED.add()
            raise InjectedCrash(f"injected crash at step {step}")
        f = self.take("stall", step)
        if f is not None:
            _mstats.FAULTS_INJECTED.add()
            time.sleep(f.secs)
        f = self.take("preempt", step)
        if f is not None:
            _mstats.FAULTS_INJECTED.add()
            signal.raise_signal(signal.SIGTERM)
        f = self.take("nan_grad", step)
        if f is not None:
            _mstats.FAULTS_INJECTED.add()
            batch = _corrupt_batch(batch)
        return batch

    def on_input(self, index: int) -> None:
        """Input-pipeline hook (io/prefetch.py producer, keyed by batch
        index)."""
        for f in self.faults:
            if f.kind != "input_stall" or f.spent():
                continue
            if (f.step is not None and index >= f.step) or \
                    (f.p is not None and f._rng.random() < f.p):
                f.consume()
                _mstats.FAULTS_INJECTED.add()
                time.sleep(f.secs)

    def on_ckpt_io(self) -> None:
        """Checkpoint-save hook: raises a transient OSError."""
        f = self.chance("ckpt_io_error")
        if f is None:
            for g in self.faults:
                if g.kind == "ckpt_io_error" and g.step is not None \
                        and not g.spent():
                    g.consume()
                    f = g
                    break
        if f is not None:
            _mstats.FAULTS_INJECTED.add()
            raise OSError("injected transient checkpoint I/O error")


FAULTS = FaultRegistry()


def configure_faults(spec: str) -> None:
    """Programmatic twin of ``paddle.set_flags({"FLAGS_fault_inject": ...})``."""
    FAULTS.configure(spec)
    _native.fault_inject[0] = spec or ""


# wire the flag cell: paddle.set_flags({"FLAGS_fault_inject": "..."}) (and
# the env default read at import) reconfigure the registry immediately
_native.fault_inject_watchers.append(FAULTS.configure)
if _native.fault_inject[0]:
    FAULTS.configure(_native.fault_inject[0])
