"""Regularizers (reference python/paddle/regularizer.py, fluid/regularizer.py)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    pass


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self.coeff})"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self.coeff})"
