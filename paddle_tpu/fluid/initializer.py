"""fluid.initializer compatibility (reference fluid/initializer.py)."""
from ..nn.initializer import (  # noqa: F401
    Assign, Bilinear, Constant, Dirac, KaimingNormal, KaimingUniform,
    Normal, Orthogonal, TruncatedNormal, Uniform, XavierNormal,
    XavierUniform, set_global_initializer,
)
from ..nn.initializer import (  # noqa: F401
    ConstantInitializer, MSRAInitializer, NormalInitializer,
    NumpyArrayInitializer, TruncatedNormalInitializer, UniformInitializer,
    XavierInitializer,
)

Xavier = XavierInitializer
MSRA = MSRAInitializer
BilinearInitializer = Bilinear
