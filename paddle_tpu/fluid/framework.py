"""fluid.framework compatibility (reference fluid/framework.py)."""
from ..framework.core import Parameter, Tensor  # noqa: F401
from ..static import (  # noqa: F401
    Block, Operator, Program, Variable, default_main_program,
    default_startup_program, device_guard, name_scope, program_guard,
)
def in_dygraph_mode():
    from .. import in_dynamic_mode

    return in_dynamic_mode()


class ParamBase(Parameter):
    """1.x alias of Parameter."""
