"""fluid.clip compatibility (reference fluid/clip.py)."""
from ..nn import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
