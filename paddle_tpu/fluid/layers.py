"""fluid.layers compatibility surface (reference python/paddle/fluid/layers/).

Re-exports the 2.x ops under their fluid-1.x names, with thin adapters
where the fluid signature differs (reduce_* dim/keep_dim, elementwise_*
axis broadcasting, probability-input cross_entropy, expand's repeat-times
semantics, 2-D flatten). LoD-coupled ops (dynamic_lstm/dynamic_gru,
lod_reset, op-level beam_search) follow the padded-dense decision in the
README — their replacements are paddle.nn.RNN/LSTM/GRU, the lengths-based
sequence ops, and nn.decode.BeamSearchDecoder/dynamic_decode.
"""
from __future__ import annotations

import builtins

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from .. import tensor as _T
from ..nn import functional as _F
from ..static import accuracy, auc, py_func, Print  # noqa: F401
from ..static.nn import (  # noqa: F401
    batch_norm, bilinear_tensor_product, case, cond, conv2d,
    conv2d_transpose, conv3d, conv3d_transpose, crf_decoding, data_norm,
    deform_conv2d, embedding, group_norm, instance_norm, layer_norm,
    multi_box_head, nce, prelu, row_conv, sequence_concat, sequence_conv,
    sequence_enumerate, sequence_expand, sequence_expand_as,
    sequence_first_step, sequence_last_step, sequence_pad, sequence_pool,
    sequence_reshape, sequence_reverse, sequence_scatter, sequence_slice,
    sequence_softmax, sequence_unpad, sparse_embedding, spectral_norm,
    switch_case, while_loop,
)
from ..static import create_global_var  # noqa: F401
from ..tensor.creation import create_parameter  # noqa: F401

# direct re-exports: same name, same semantics
from ..tensor import (  # noqa: F401
    abs, cast, ceil, clip, concat, cos, cumsum, equal, exp, floor, gather,
    gather_nd, greater_equal, greater_than, increment, less_equal,
    less_than, log, logical_and, logical_not, logical_or, logical_xor,
    not_equal, ones, ones_like, pow, reciprocal, round, rsqrt, scale,
    scatter, shard_index, sign, sin, slice, sqrt, square, squeeze, stack,
    tanh, transpose, unsqueeze, unstack, zeros, zeros_like, shape,
    reverse, scatter_nd, scatter_nd_add, argmax, argmin, argsort, sort,
    topk, nonzero, split,
)
from ..nn.functional import (  # noqa: F401
    elu, gelu, hardshrink, hardsigmoid, hardswish, leaky_relu, log_loss,
    log_softmax, maxout, relu, relu6, selu, sigmoid, softmax, softplus,
    softshrink, softsign, swish, thresholded_relu, label_smooth,
    sigmoid_focal_loss, square_error_cost, softmax_with_cross_entropy,
    gather_tree, temporal_shift, affine_grid, one_hot,
    kl_div, npair_loss, edit_distance, sequence_mask, unfold,
    pixel_shuffle,
)
from ..nn.functional import grid_sample as grid_sampler  # noqa: F401
from ..vision.ops import (  # noqa: F401
    anchor_generator, box_clip, box_coder, bipartite_match,
    collect_fpn_proposals, distribute_fpn_proposals, generate_proposals,
    iou_similarity, matrix_nms, multiclass_nms, prior_box, psroi_pool,
    roi_align, roi_pool, yolo_box,
)
from ..vision.ops import yolo_loss as yolov3_loss  # noqa: F401
from ..text import viterbi_decode  # noqa: F401


def fc(input=None, size=None, num_flatten_dims=1, param_attr=None,  # noqa: A002
       bias_attr=None, act=None, name=None, x=None, activation=None,
       weight_attr=None):
    """1.x fc spelling (input=/act=/param_attr=) over static.nn.fc."""
    from ..static.nn import fc as _fc

    return _fc(input if input is not None else x, size,
               num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr if param_attr is not None else weight_attr,
               bias_attr=bias_attr,
               activation=act if act is not None else activation, name=name)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from ..tensor.creation import full

    return full(shape, value, dtype=dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0):
    from ..tensor.creation import full

    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return full(shape, value, dtype=dtype)


def assign(input, output=None):  # noqa: A002
    from ..tensor.creation import assign as _assign

    return _assign(input, output)


def _reduce(fn, input, dim, keep_dim):  # noqa: A002
    if isinstance(dim, (list, tuple)):
        dim = [int(d) for d in dim]
    return fn(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.sum, input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.mean, input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.max, input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.min, input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.prod, input, dim, keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.all, input, dim, keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.any, input, dim, keep_dim)


def mean(x, name=None):
    return _T.mean(x)


def _ew_axis(y, x_ndim, axis):
    """fluid elementwise axis semantics: y's dims align to x starting at
    ``axis`` (elementwise_op_function.h GetMidDims)."""
    if axis == -1 or y.ndim == x_ndim:
        return y
    pad_right = x_ndim - axis - y.ndim
    return _T.reshape(y, list(y.shape) + [1] * pad_right)


def _make_elementwise(fn, name):
    def op(x, y, axis=-1, act=None, name=None):
        out = fn(x, _ew_axis(y, x.ndim, axis))
        if act is not None:
            out = getattr(_F, act)(out)
        return out

    op.__name__ = name
    return op


elementwise_add = _make_elementwise(_T.add, "elementwise_add")
elementwise_sub = _make_elementwise(_T.subtract, "elementwise_sub")
elementwise_mul = _make_elementwise(_T.multiply, "elementwise_mul")
elementwise_div = _make_elementwise(_T.divide, "elementwise_div")
elementwise_max = _make_elementwise(_T.maximum, "elementwise_max")
elementwise_min = _make_elementwise(_T.minimum, "elementwise_min")
elementwise_pow = _make_elementwise(_T.pow, "elementwise_pow")
elementwise_mod = _make_elementwise(_T.remainder, "elementwise_mod")
elementwise_floordiv = _make_elementwise(_T.floor_divide,
                                         "elementwise_floordiv")


def _mul_impl(x, y, x_num_col_dims=1, y_num_col_dims=1):
    xm = x.reshape((int(np.prod(x.shape[:x_num_col_dims])), -1))
    ym = y.reshape((int(np.prod(y.shape[:y_num_col_dims])), -1))
    out = xm @ ym
    # mul_op shape inference: x.shape[:xd] + y.shape[yd:]
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """fluid.layers.mul (mul_op.cc): flatten both sides to 2-D and matmul."""
    return apply_op(_mul_impl, x, y, x_num_col_dims=int(x_num_col_dims),
                    y_num_col_dims=int(y_num_col_dims), op_name="mul")


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = _T.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if alpha != 1.0:
        out = out * alpha
    return out


def _ce_soft_impl(p, l):
    return -jnp.sum(l * jnp.log(jnp.maximum(p, 1e-20)), axis=-1,
                    keepdims=True)


def _ce_prob_impl(p, label, ignore_index):
    lab = label.reshape(p.shape[:-1])
    picked = jnp.take_along_axis(p, lab[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    loss = jnp.where(lab == ignore_index, 0.0, loss)
    return loss[..., None]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):  # noqa: A002
    """fluid cross_entropy takes PROBABILITIES (post-softmax), unlike 2.x
    F.cross_entropy's logits (reference cross_entropy_op.h)."""
    if soft_label:
        return apply_op(_ce_soft_impl, input, label,
                        op_name="cross_entropy_soft")
    return apply_op(_ce_prob_impl, input, label,
                    ignore_index=int(ignore_index), op_name="cross_entropy")


def dropout(x, dropout_prob, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    if global_pooling:
        if pool_type == "max":
            return _F.adaptive_max_pool2d(input, 1)
        return _F.adaptive_avg_pool2d(input, 1)
    if pool_type == "max":
        return _F.max_pool2d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode)
    return _F.avg_pool2d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode,
                         exclusive=exclusive)


def flatten(x, axis=1, name=None):
    """fluid flatten → 2-D [prod(shape[:axis]), prod(shape[axis:])]."""
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return _T.reshape(x, [lead, -1])


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):  # noqa: A002
    out = _T.reshape(x, shape)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def expand(x, expand_times, name=None):
    """fluid expand repeats each dim ``expand_times[i]`` times (2.x tile)."""
    return _T.tile(x, expand_times)


def expand_as(x, target_tensor, name=None):
    return _T.expand_as(x, target_tensor)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    from ..tensor.random import uniform

    return uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    from ..tensor.random import normal

    out = normal(mean=mean, std=std, shape=shape)
    return _T.cast(out, dtype) if str(out.dtype) != dtype else out


def range(start, end, step, dtype, name=None):  # noqa: A002
    from ..tensor.creation import arange

    return arange(start, end, step, dtype)


def linspace(start, stop, num, dtype=None, name=None):
    from ..tensor.creation import linspace as _linspace

    return _linspace(start, stop, num, dtype)


def _smooth_l1_impl(x, y, ow, sigma2):
    d = x - y
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * d * d * sigma2,
                     ad - 0.5 / sigma2)
    loss = loss * ow  # elementwise, BEFORE the per-row sum (smooth_l1_op.h)
    return jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """smooth_l1_op.cc: per-row summed smooth-L1; inside_weight scales the
    diff, outside_weight scales each element's loss."""
    sigma2 = (sigma if sigma is not None else 1.0) ** 2
    if inside_weight is not None:
        x = _T.multiply(x, inside_weight)
        y = _T.multiply(y, inside_weight)
    if outside_weight is None:
        outside_weight = Tensor(jnp.ones((1, 1), jnp.float32))
    return apply_op(_smooth_l1_impl, x, y, outside_weight,
                    sigma2=float(sigma2), op_name="smooth_l1")


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    def _impl(x, lab, ignore_index, normalize):
        loss = jnp.maximum(x, 0.0) - x * lab + jnp.log1p(jnp.exp(-jnp.abs(x)))
        keep = lab != ignore_index
        loss = jnp.where(keep, loss, 0.0)
        if normalize:
            loss = loss / jnp.maximum(jnp.sum(keep), 1)
        return loss

    return apply_op(_impl, x, label, ignore_index=int(ignore_index),
                    normalize=bool(normalize),
                    op_name="sigmoid_cross_entropy_with_logits")


def clip_by_norm(x, max_norm, name=None):
    def _impl(x, max_norm):
        n = jnp.sqrt(jnp.sum(x * x))
        return jnp.where(n > max_norm, x * (max_norm / n), x)

    return apply_op(_impl, x, max_norm=float(max_norm),
                    op_name="clip_by_norm")


def where(condition):
    """fluid.layers.where = indices of True (2.x nonzero)."""
    return _T.nonzero(condition)


def has_nan(x):
    return _T.any(_T.isnan(x))


def has_inf(x):
    return _T.any(_T.isinf(x))


def isfinite(x):
    return _T.all(_T.isfinite(x))


def resize_bilinear(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                    align_mode=1, data_format="NCHW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="bilinear", align_corners=align_corners)


def resize_nearest(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                   data_format="NCHW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="nearest")


def _pad_impl(x, paddings, pad_value):
    pw = []
    for i in builtins.range(x.ndim):
        pw.append((paddings[2 * i], paddings[2 * i + 1]))
    return jnp.pad(x, pw, constant_values=pad_value)


def pad(x, paddings, pad_value=0.0, name=None):
    """fluid pad: flat (before, after) per dim."""
    pw = tuple(int(p) for p in paddings)
    return apply_op(_pad_impl, x, paddings=pw, pad_value=float(pad_value),
                    op_name="pad")


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _F.hardsigmoid(x, slope=slope, offset=offset)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _F.hardtanh(x, min=t_min, max=t_max)


def soft_relu(x, threshold=40.0, name=None):
    return apply_op(
        lambda x, threshold: jnp.log1p(jnp.exp(jnp.clip(x, -threshold,
                                                        threshold))),
        x, threshold=float(threshold), op_name="soft_relu")


def relu_clipped(x, threshold=6.0, name=None):
    return _T.clip(_F.relu(x), 0.0, threshold)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    def _impl(x, axis, epsilon):
        n = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=axis, keepdims=True),
                                 epsilon))
        return x / n

    return apply_op(_impl, x, axis=int(axis), epsilon=float(epsilon),
                    op_name="l2_normalize")


def create_tensor(dtype, name=None, persistable=False):
    from ..framework import dtype as dtypes

    return Tensor(jnp.zeros((), dtypes.convert_dtype(dtype)), name=name)


def array_write(x, i, array=None):
    """LoDTensorArray shim: python list + index (control-flow arrays are
    lax.scan carries in compiled code; this covers eager parity tests)."""
    if array is None:
        array = []
    idx = int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[int(i)]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


def create_array(dtype):
    return []
