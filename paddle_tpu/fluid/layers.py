"""fluid.layers compatibility surface (reference python/paddle/fluid/layers/).

Re-exports the 2.x ops under their fluid-1.x names, with thin adapters
where the fluid signature differs (reduce_* dim/keep_dim, elementwise_*
axis broadcasting, probability-input cross_entropy, expand's repeat-times
semantics, 2-D flatten). LoD-coupled ops (dynamic_lstm/dynamic_gru,
lod_reset, op-level beam_search) follow the padded-dense decision in the
README — their replacements are paddle.nn.RNN/LSTM/GRU, the lengths-based
sequence ops, and nn.decode.BeamSearchDecoder/dynamic_decode.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op, _is_tracer
from .. import tensor as _T
from ..nn import functional as _F
from ..static import accuracy, auc, py_func, Print  # noqa: F401
from ..static.nn import (  # noqa: F401
    batch_norm, bilinear_tensor_product, case, cond, conv2d,
    conv2d_transpose, conv3d, conv3d_transpose, crf_decoding, data_norm,
    deform_conv2d, embedding, group_norm, instance_norm, layer_norm,
    multi_box_head, nce, prelu, row_conv, sequence_concat, sequence_conv,
    sequence_enumerate, sequence_expand, sequence_expand_as,
    sequence_first_step, sequence_last_step, sequence_pad, sequence_pool,
    sequence_reshape, sequence_reverse, sequence_scatter, sequence_slice,
    sequence_softmax, sequence_unpad, sparse_embedding, spectral_norm,
    switch_case, while_loop,
)
from ..static import create_global_var  # noqa: F401
from ..tensor.creation import create_parameter  # noqa: F401

# direct re-exports: same name, same semantics
from ..tensor import (  # noqa: F401
    abs, cast, ceil, clip, concat, cos, cumsum, equal, exp, floor, gather,
    gather_nd, greater_equal, greater_than, increment, less_equal,
    less_than, log, logical_and, logical_not, logical_or, logical_xor,
    not_equal, ones, ones_like, pow, reciprocal, round, rsqrt, scale,
    scatter, shard_index, sign, sin, slice, sqrt, square, squeeze, stack,
    tanh, transpose, unsqueeze, unstack, zeros, zeros_like, shape,
    reverse, scatter_nd, scatter_nd_add, argmax, argmin, argsort, sort,
    topk, nonzero, split,
)
from ..nn.functional import (  # noqa: F401
    elu, gelu, hardshrink, hardsigmoid, hardswish, leaky_relu, log_loss,
    log_softmax, maxout, relu, relu6, selu, sigmoid, softmax, softplus,
    softshrink, softsign, swish, thresholded_relu, label_smooth,
    sigmoid_focal_loss, square_error_cost, softmax_with_cross_entropy,
    gather_tree, temporal_shift, affine_grid, one_hot,
    kl_div, npair_loss, edit_distance, sequence_mask, unfold,
    pixel_shuffle,
)
from ..nn.functional import grid_sample as grid_sampler  # noqa: F401
from ..vision.ops import (  # noqa: F401
    anchor_generator, box_clip, box_coder, bipartite_match,
    collect_fpn_proposals, distribute_fpn_proposals, generate_proposals,
    iou_similarity, matrix_nms, multiclass_nms, prior_box, psroi_pool,
    roi_align, roi_pool, yolo_box,
)
from ..vision.ops import yolo_loss as yolov3_loss  # noqa: F401
from ..text import viterbi_decode  # noqa: F401


def fc(input=None, size=None, num_flatten_dims=1, param_attr=None,  # noqa: A002
       bias_attr=None, act=None, name=None, x=None, activation=None,
       weight_attr=None):
    """1.x fc spelling (input=/act=/param_attr=) over static.nn.fc."""
    from ..static.nn import fc as _fc

    return _fc(input if input is not None else x, size,
               num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr if param_attr is not None else weight_attr,
               bias_attr=bias_attr,
               activation=act if act is not None else activation, name=name)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from ..tensor.creation import full

    return full(shape, value, dtype=dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0):
    from ..tensor.creation import full

    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return full(shape, value, dtype=dtype)


def assign(input, output=None):  # noqa: A002
    from ..tensor.creation import assign as _assign

    return _assign(input, output)


def _reduce(fn, input, dim, keep_dim):  # noqa: A002
    if isinstance(dim, (list, tuple)):
        dim = [int(d) for d in dim]
    return fn(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.sum, input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.mean, input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.max, input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.min, input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.prod, input, dim, keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.all, input, dim, keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce(_T.any, input, dim, keep_dim)


def mean(x, name=None):
    return _T.mean(x)


def _ew_axis(y, x_ndim, axis):
    """fluid elementwise axis semantics: y's dims align to x starting at
    ``axis`` (elementwise_op_function.h GetMidDims)."""
    if axis == -1 or y.ndim == x_ndim:
        return y
    pad_right = x_ndim - axis - y.ndim
    return _T.reshape(y, list(y.shape) + [1] * pad_right)


def _make_elementwise(fn, name):
    def op(x, y, axis=-1, act=None, name=None):
        out = fn(x, _ew_axis(y, x.ndim, axis))
        if act is not None:
            out = getattr(_F, act)(out)
        return out

    op.__name__ = name
    return op


elementwise_add = _make_elementwise(_T.add, "elementwise_add")
elementwise_sub = _make_elementwise(_T.subtract, "elementwise_sub")
elementwise_mul = _make_elementwise(_T.multiply, "elementwise_mul")
elementwise_div = _make_elementwise(_T.divide, "elementwise_div")
elementwise_max = _make_elementwise(_T.maximum, "elementwise_max")
elementwise_min = _make_elementwise(_T.minimum, "elementwise_min")
elementwise_pow = _make_elementwise(_T.pow, "elementwise_pow")
elementwise_mod = _make_elementwise(_T.remainder, "elementwise_mod")
elementwise_floordiv = _make_elementwise(_T.floor_divide,
                                         "elementwise_floordiv")


def _mul_impl(x, y, x_num_col_dims=1, y_num_col_dims=1):
    xm = x.reshape((int(np.prod(x.shape[:x_num_col_dims])), -1))
    ym = y.reshape((int(np.prod(y.shape[:y_num_col_dims])), -1))
    out = xm @ ym
    # mul_op shape inference: x.shape[:xd] + y.shape[yd:]
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """fluid.layers.mul (mul_op.cc): flatten both sides to 2-D and matmul."""
    return apply_op(_mul_impl, x, y, x_num_col_dims=int(x_num_col_dims),
                    y_num_col_dims=int(y_num_col_dims), op_name="mul")


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = _T.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if alpha != 1.0:
        out = out * alpha
    return out


def _ce_soft_impl(p, l):
    return -jnp.sum(l * jnp.log(jnp.maximum(p, 1e-20)), axis=-1,
                    keepdims=True)


def _ce_prob_impl(p, label, ignore_index):
    lab = label.reshape(p.shape[:-1])
    picked = jnp.take_along_axis(p, lab[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    loss = jnp.where(lab == ignore_index, 0.0, loss)
    return loss[..., None]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):  # noqa: A002
    """fluid cross_entropy takes PROBABILITIES (post-softmax), unlike 2.x
    F.cross_entropy's logits (reference cross_entropy_op.h)."""
    if soft_label:
        return apply_op(_ce_soft_impl, input, label,
                        op_name="cross_entropy_soft")
    return apply_op(_ce_prob_impl, input, label,
                    ignore_index=int(ignore_index), op_name="cross_entropy")


def dropout(x, dropout_prob, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    if data_format != "NCHW":
        raise NotImplementedError(
            "pool2d: NHWC is not wired through the pooling functionals; "
            "transpose to NCHW (XLA lays out for the TPU regardless)")
    if global_pooling:
        if pool_type == "max":
            return _F.adaptive_max_pool2d(input, 1)
        return _F.adaptive_avg_pool2d(input, 1)
    if pool_type == "max":
        return _F.max_pool2d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode)
    return _F.avg_pool2d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode,
                         exclusive=exclusive)


def flatten(x, axis=1, name=None):
    """fluid flatten → 2-D [prod(shape[:axis]), prod(shape[axis:])]."""
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return _T.reshape(x, [lead, -1])


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):  # noqa: A002
    out = _T.reshape(x, shape)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def expand(x, expand_times, name=None):
    """fluid expand repeats each dim ``expand_times[i]`` times (2.x tile)."""
    return _T.tile(x, expand_times)


def expand_as(x, target_tensor, name=None):
    return _T.expand_as(x, target_tensor)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    from ..tensor.random import uniform

    return uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    from ..tensor.random import normal

    out = normal(mean=mean, std=std, shape=shape)
    return _T.cast(out, dtype) if str(out.dtype) != dtype else out


def range(start, end, step, dtype, name=None):  # noqa: A002
    from ..tensor.creation import arange

    return arange(start, end, step, dtype)


def linspace(start, stop, num, dtype=None, name=None):
    from ..tensor.creation import linspace as _linspace

    return _linspace(start, stop, num, dtype)


def _smooth_l1_impl(x, y, ow, sigma2):
    d = x - y
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * d * d * sigma2,
                     ad - 0.5 / sigma2)
    loss = loss * ow  # elementwise, BEFORE the per-row sum (smooth_l1_op.h)
    return jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """smooth_l1_op.cc: per-row summed smooth-L1; inside_weight scales the
    diff, outside_weight scales each element's loss."""
    sigma2 = (sigma if sigma is not None else 1.0) ** 2
    if inside_weight is not None:
        x = _T.multiply(x, inside_weight)
        y = _T.multiply(y, inside_weight)
    if outside_weight is None:
        outside_weight = Tensor(jnp.ones((1, 1), jnp.float32))
    return apply_op(_smooth_l1_impl, x, y, outside_weight,
                    sigma2=float(sigma2), op_name="smooth_l1")


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    def _impl(x, lab, ignore_index, normalize):
        loss = jnp.maximum(x, 0.0) - x * lab + jnp.log1p(jnp.exp(-jnp.abs(x)))
        keep = lab != ignore_index
        loss = jnp.where(keep, loss, 0.0)
        if normalize:
            loss = loss / jnp.maximum(jnp.sum(keep), 1)
        return loss

    return apply_op(_impl, x, label, ignore_index=int(ignore_index),
                    normalize=bool(normalize),
                    op_name="sigmoid_cross_entropy_with_logits")


def clip_by_norm(x, max_norm, name=None):
    def _impl(x, max_norm):
        n = jnp.sqrt(jnp.sum(x * x))
        return jnp.where(n > max_norm, x * (max_norm / n), x)

    return apply_op(_impl, x, max_norm=float(max_norm),
                    op_name="clip_by_norm")


def where(condition):
    """fluid.layers.where = indices of True (2.x nonzero)."""
    return _T.nonzero(condition)


def has_nan(x):
    return _T.any(_T.isnan(x))


def has_inf(x):
    return _T.any(_T.isinf(x))


def isfinite(x):
    return _T.all(_T.isfinite(x))


def resize_bilinear(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                    align_mode=1, data_format="NCHW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="bilinear", align_corners=align_corners)


def resize_nearest(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                   data_format="NCHW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="nearest")


def _pad_impl(x, paddings, pad_value):
    pw = []
    for i in builtins.range(x.ndim):
        pw.append((paddings[2 * i], paddings[2 * i + 1]))
    return jnp.pad(x, pw, constant_values=pad_value)


def pad(x, paddings, pad_value=0.0, name=None):
    """fluid pad: flat (before, after) per dim."""
    pw = tuple(int(p) for p in paddings)
    return apply_op(_pad_impl, x, paddings=pw, pad_value=float(pad_value),
                    op_name="pad")


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _F.hardsigmoid(x, slope=slope, offset=offset)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _F.hardtanh(x, min=t_min, max=t_max)


def soft_relu(x, threshold=40.0, name=None):
    return apply_op(
        lambda x, threshold: jnp.log1p(jnp.exp(jnp.clip(x, -threshold,
                                                        threshold))),
        x, threshold=float(threshold), op_name="soft_relu")


def relu_clipped(x, threshold=6.0, name=None):
    return _T.clip(_F.relu(x), 0.0, threshold)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    def _impl(x, axis, epsilon):
        n = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=axis, keepdims=True),
                                 epsilon))
        return x / n

    return apply_op(_impl, x, axis=int(axis), epsilon=float(epsilon),
                    op_name="l2_normalize")


def create_tensor(dtype, name=None, persistable=False):
    from ..framework import dtype as dtypes

    return Tensor(jnp.zeros((), dtypes.convert_dtype(dtype)), name=name)


def array_write(x, i, array=None):
    """LoDTensorArray shim: python list + index (control-flow arrays are
    lax.scan carries in compiled code; this covers eager parity tests)."""
    if array is None:
        array = []
    idx = int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[int(i)]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


def create_array(dtype):
    return []


# --- second batch: remaining fluid.layers names -----------------------------
# re-exports
from ..tensor import (  # noqa: F401,E402
    crop, diag, eye, multiplex, rank, strided_slice, sum, triu, unbind,
    unique, unique_consecutive, stanh, numel as size,
)
from ..tensor import add_n as sums  # noqa: F401,E402  (sum_op: elementwise list add)
from ..nn.functional import (  # noqa: F401,E402
    dice_loss, mse_loss, mish, ctc_loss as warpctc,
    hardswish as hard_swish, kl_div as kldiv_loss,
    adaptive_avg_pool2d as adaptive_pool2d,
    adaptive_avg_pool3d as adaptive_pool3d, interpolate as image_resize,
    pixel_unshuffle as space_to_depth,
)


def _huber_impl(x, y, delta):
    d = y - x
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


def huber_loss(input, label, delta):  # noqa: A002
    """huber_loss_op: elementwise Huber residual (no reduction),
    1.x positional delta."""
    return apply_op(_huber_impl, input, label, delta=float(delta),
                    op_name="huber_loss")
from ..nn.decode import BeamSearchDecoder, dynamic_decode  # noqa: F401,E402
from ..nn import GRUCell, LSTMCell, RNNCellBase as RNNCell  # noqa: F401,E402
from ..distribution import Categorical, Normal, Uniform  # noqa: F401,E402
from ..static import data  # noqa: F401,E402
from ..text import linear_chain_crf  # noqa: F401,E402
from ..vision.ops import (  # noqa: F401,E402
    deform_conv2d as deformable_conv, psroi_pool as prroi_pool,
    read_file,
)

crop_tensor = crop


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,  # noqa: A002
          data_format="NCHW", name=None):
    """fluid pad2d: [top, bottom, left, right] on the spatial dims."""
    t, b, lft, r = (int(v) for v in paddings)
    return _F.pad(input, [lft, r, t, b], mode=mode, value=pad_value,
                  data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with trailing constants (pad_constant_like_op)."""
    pads = []
    for xs, ys in zip(x.shape, y.shape):
        pads += [0, int(xs) - int(ys)]
    return _pad_via_flat(y, pads, pad_value)


def _pad_via_flat(y, pads, pad_value):
    return pad(y, pads, pad_value=pad_value)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCDHW", name=None):
    if global_pooling:
        if pool_type == "max":
            return _F.adaptive_max_pool3d(input, 1)
        return _F.adaptive_avg_pool3d(input, 1)
    if pool_type == "max":
        return _F.max_pool3d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode)
    return _F.avg_pool3d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode)


def resize_linear(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                  align_mode=1, data_format="NCW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="linear", align_corners=align_corners)


def resize_trilinear(input, out_shape=None, scale=None, align_corners=True,  # noqa: A002
                     align_mode=1, data_format="NCDHW", name=None):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="trilinear", align_corners=align_corners)


def image_resize_short(input, out_short_len, resample="BILINEAR"):  # noqa: A002
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    ratio = out_short_len / float(short)
    return _F.interpolate(input, size=[int(round(h * ratio)),
                                       int(round(w * ratio))],
                          mode=resample.lower())


def cos_sim(X, Y):  # noqa: N803
    """cos_sim_op: row-wise cosine similarity → [N, 1]."""
    out = _F.cosine_similarity(X, Y, axis=-1)
    return _T.unsqueeze(out, -1)


def _mean_iou_impl(pred, label, num_classes):
    pred = pred.reshape(-1).astype(jnp.int32)
    lab = label.reshape(-1).astype(jnp.int32)
    idx = pred * num_classes + lab
    cm = jnp.zeros((num_classes * num_classes,), jnp.float32).at[idx].add(1.0)
    cm = cm.reshape(num_classes, num_classes)
    inter = jnp.diagonal(cm)
    union = jnp.sum(cm, 0) + jnp.sum(cm, 1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-9), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    # reference output order: (mean_iou, out_wrong, out_correct)
    return miou, (union - inter).astype(jnp.int32), inter.astype(jnp.int32)


def mean_iou(input, label, num_classes):  # noqa: A002
    """mean_iou_op outputs (mean_iou, out_wrong, out_correct): per-class
    difference counts then intersection counts, like the reference."""
    return apply_op(_mean_iou_impl, input, label,
                    num_classes=int(num_classes), op_name="mean_iou")


def _rank_loss_impl(label, left, right):
    p = jax.nn.sigmoid(left - right)
    return -label * jnp.log(jnp.maximum(p, 1e-20)) \
        - (1.0 - label) * jnp.log(jnp.maximum(1.0 - p, 1e-20))


def rank_loss(label, left, right, name=None):
    """rank_loss_op: RankNet pairwise loss."""
    return apply_op(_rank_loss_impl, label, left, right, op_name="rank_loss")


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """margin_rank_loss_op: max(0, -label*(left-right) + margin)."""
    def _impl(label, left, right, margin):
        return jnp.maximum(0.0, -label * (left - right) + margin)

    return apply_op(_impl, label, left, right, margin=float(margin),
                    op_name="margin_rank_loss")


def _bpr_loss_impl(x, label):
    lab = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = x - pos
    # exclude the positive column itself, like bpr_loss_op
    mask = jnp.arange(x.shape[1])[None, :] != lab[:, None]
    loss = jnp.where(mask, jnp.log1p(jnp.exp(diff)), 0.0)
    return jnp.sum(loss, axis=1, keepdims=True) / jnp.maximum(
        x.shape[1] - 1, 1)


def bpr_loss(input, label, name=None):  # noqa: A002
    """bpr_loss_op: Bayesian personalized ranking over score rows."""
    return apply_op(_bpr_loss_impl, input, label, op_name="bpr_loss")


def shuffle_channel(x, group, name=None):
    def _impl(x, group):
        n, c, h, w = x.shape
        return x.reshape(n, group, c // group, h, w).swapaxes(1, 2).reshape(
            n, c, h, w)

    return apply_op(_impl, x, group=int(group), op_name="shuffle_channel")


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):  # noqa: A002
    """sampling_id_op: sample a category per row of probabilities."""
    from ..framework.random import next_key

    key = jax.random.PRNGKey(seed) if seed else next_key()
    probs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    idx = jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-20)),
                                 axis=-1)
    return Tensor(idx)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(
        (x.size if isinstance(x, Tensor) else np.size(x)) == 0))


def Assert(cond, data=None, summarize=20, name=None):  # noqa: N802
    ok = bool(cond.numpy()) if isinstance(cond, Tensor) else bool(cond)
    if not ok:
        raise AssertionError(
            "fluid.layers.Assert failed"
            + ("" if data is None else ": %s" % ([np.asarray(getattr(
                d, "_data", d)) for d in data],)))
    return cond


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    key = counter_name or "@STEP_COUNTER@"
    from ..static import default_main_program

    prog = default_main_program()
    counters = getattr(prog, "_step_counters", None)
    if counters is None:
        counters = prog._step_counters = {}
    val = counters.get(key, begin - step) + step
    counters[key] = val
    return Tensor(jnp.asarray(val, jnp.int64))


def uniform_random_batch_size_like(input, shape, dtype="float32",  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max, seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,  # noqa: A002
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shape, mean, std, seed, dtype)


# --- LR decay functions → the scheduler objects our optimizers consume
#     (reference layers/learning_rate_scheduler.py builds graph ops; here
#     schedules are host-side LRScheduler state, the 2.x design) ----------

def _ratio(step, decay_steps, staircase):
    r = step / float(decay_steps)
    return np.floor(r) if staircase else r


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr = base * rate^(step/decay_steps) (learning_rate_scheduler.py)."""
    from ..optimizer.lr import LambdaDecay

    return LambdaDecay(learning_rate, lambda step: decay_rate ** _ratio(
        step, decay_steps, staircase))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer.lr import LambdaDecay

    return LambdaDecay(learning_rate, lambda step: float(np.exp(
        -decay_rate * _ratio(step, decay_steps, staircase))))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ..optimizer.lr import LambdaDecay

    return LambdaDecay(learning_rate, lambda step: 1.0 / (
        1.0 + decay_rate * _ratio(step, decay_steps, staircase)))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from ..optimizer.lr import PolynomialDecay

    return PolynomialDecay(learning_rate, decay_steps,
                           end_lr=end_learning_rate, power=power, cycle=cycle)


def piecewise_decay(boundaries, values):
    from ..optimizer.lr import PiecewiseDecay

    return PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr = base * 0.5 * (cos(epoch*pi/epochs) + 1), epoch = step //
    step_each_epoch (learning_rate_scheduler.py cosine_decay)."""
    from ..optimizer.lr import LambdaDecay

    return LambdaDecay(learning_rate, lambda step: 0.5 * (float(np.cos(
        (step // step_each_epoch) * np.pi / epochs)) + 1.0))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ..optimizer.lr import NoamDecay

    return NoamDecay(d_model, warmup_steps, learning_rate=learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ..optimizer.lr import LinearWarmup

    return LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# --- control-flow class shims over the functional forms ------------------

class While:
    """fluid.layers.While block → use while_loop; kept as a guidance shim
    (the reference's block-style API writes into a Program block, which the
    traced design expresses as lax.while via static.nn.while_loop)."""

    def __init__(self, cond, is_test=False, name=None):
        raise NotImplementedError(
            "block-style While is not supported: express the loop with "
            "fluid.layers.while_loop(cond_fn, body_fn, loop_vars) — same "
            "semantics, compiled to lax.while_loop")


class Switch:
    def __init__(self, name=None):
        raise NotImplementedError(
            "block-style Switch is not supported: use "
            "fluid.layers.case/switch_case")


class IfElse:
    def __init__(self, cond, name=None):
        raise NotImplementedError(
            "block-style IfElse is not supported: use fluid.layers.cond")


# --- third batch: functional rnn, remaining impls, guided refusals ----------

from ..nn.functional import local_response_norm as lrn  # noqa: F401,E402


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Functional rnn over a cell (reference layers/rnn.py rnn)."""
    from ..nn import RNN

    return RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    from ..nn import BiRNN

    return BiRNN(cell_fw, cell_bw, time_major=time_major)(
        inputs, initial_states, sequence_length)


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,  # noqa: A002
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """fluid cudnn-style lstm → nn.LSTM (weights created per call like the
    other static helpers)."""
    from ..nn import LSTM

    # 1.x cudnn lstm is sequence-major: input [seq_len, batch, input_dim]
    net = LSTM(int(input.shape[-1]), hidden_size, num_layers=num_layers,
               direction="bidirect" if is_bidirec else "forward",
               dropout=dropout_prob, time_major=True)
    out, (h, c) = net(input, (init_h, init_c))
    return out, h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,  # noqa: A002
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """Single GRU step (gru_unit_op): input is the pre-projected [B, size]
    gates; returns (hidden, reset_hidden_pre, gate) like the reference."""
    from .dygraph import GRUUnit

    return GRUUnit(size, param_attr, bias_attr, activation,
                   gate_activation, origin_mode)(input, hidden)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    from ..nn import LSTMCell

    cell = LSTMCell(int(x_t.shape[-1]), int(hidden_t_prev.shape[-1]))
    out, (h, c) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return h, c


def unique_with_counts(x, dtype="int32"):
    out, idx, counts = _T.unique(x, return_inverse=True, return_counts=True)
    return out, idx, counts


def affine_channel(x, scale=None, bias=None, data_format="NCHW", act=None,
                   name=None):
    def _impl(x, scale, bias):
        s = scale.reshape(1, -1, *([1] * (x.ndim - 2)))
        b = bias.reshape(1, -1, *([1] * (x.ndim - 2)))
        return x * s + b

    out = apply_op(_impl, x, scale, bias, op_name="affine_channel")
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def _add_pos_enc_impl(x, alpha, beta):
    B, T, D = x.shape
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    half = D // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return alpha * x + beta * enc[None, :, :D]


def add_position_encoding(input, alpha, beta, name=None):  # noqa: A002
    """add_position_encoding_op: alpha*x + beta*sinusoid(position)."""
    return apply_op(_add_pos_enc_impl, input, alpha=float(alpha),
                    beta=float(beta), op_name="add_position_encoding")


def fsp_matrix(x, y):
    """fsp_op: flow-of-solution-procedure Gram matrix for distillation."""
    def _impl(x, y):
        B, C1 = x.shape[0], x.shape[1]
        C2 = y.shape[1]
        hw = x.shape[2] * x.shape[3]
        xf = x.reshape(B, C1, hw)
        yf = y.reshape(B, C2, hw)
        return jnp.einsum("bch,bdh->bcd", xf, yf) / hw

    return apply_op(_impl, x, y, op_name="fsp_matrix")


def _ts_bce(z, t):
    return jnp.maximum(z, 0.0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))


def _ts_loss_impl(z, lab, ub, lb):
    z = jnp.clip(z, lb, ub)
    # teacher_student_sigmoid_loss_op.h:44-62: label encodes
    # (teacher-score presence, click) — {-2, -1, [0,1), [1,2)}
    return jnp.where(
        lab < -1.0, _ts_bce(z, 0.0),
        jnp.where(lab < 0.0, _ts_bce(z, 1.0),
                  jnp.where(lab < 1.0, _ts_bce(z, 0.0) + _ts_bce(z, lab),
                            _ts_bce(z, 1.0) + _ts_bce(z, lab - 1.0))))


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,  # noqa: A002
                                 soft_max_lower_bound=-15.0):
    """teacher_student_sigmoid_loss_op: hard-click CE plus the soft
    teacher-score CE when the label carries one."""
    return apply_op(_ts_loss_impl, input, label,
                    ub=float(soft_max_up_bound),
                    lb=float(soft_max_lower_bound),
                    op_name="teacher_student_sigmoid_loss")


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,  # noqa: A002
                       name=None):
    """ctc_align_op greedy mode: argmax, merge repeats, drop blanks.
    Dynamic output → host-side (eager), like the reference CPU kernel."""
    probs = np.asarray(input._data if isinstance(input, Tensor)
                       else input)                    # [B, T, C]
    lens = (np.asarray(getattr(input_length, "_data", input_length)).reshape(-1)
            if input_length is not None
            else np.full((probs.shape[0],), probs.shape[1]))
    ids = probs.argmax(-1)                            # [B, T]
    rows = []
    for b in builtins.range(ids.shape[0]):
        seq, prev = [], None
        for t in builtins.range(int(lens[b])):
            tok = int(ids[b, t])
            if tok != prev and tok != blank:
                seq.append(tok)
            prev = tok
        rows.append(seq)
    T_out = builtins.max([len(r) for r in rows] + [1])
    out = np.full((len(rows), T_out), padding_value, np.int64)
    for b, r in enumerate(rows):
        out[b, :len(r)] = r
    out_lens = np.asarray([len(r) for r in rows], np.int64)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(out_lens))


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """sample_logits_op: softmax CE over the true class + sampled classes
    (uniform sampler, like nce)."""
    from ..framework.random import next_key

    key = jax.random.PRNGKey(seed) if seed else next_key()
    C = int(logits.shape[-1])
    samp = jax.random.randint(key, (int(num_samples),), 0, C)

    def _impl(logits, label, samp):
        lab = label.reshape(-1)
        pos = jnp.take_along_axis(logits, lab[:, None], axis=1)  # [B,1]
        neg = logits[:, samp]                                     # [B,S]
        z = jnp.concatenate([pos, neg], axis=1)
        return -jax.nn.log_softmax(z, axis=1)[:, :1]

    return apply_op(_impl, logits, label, Tensor(samp),
                    op_name="sampled_softmax_with_cross_entropy")


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """SSD detection_output = decode_center_size box_coder + multiclass_nms
    (reference detection.py detection_output composition)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size", box_normalized=True)
    return multiclass_nms(decoded, scores, background_label=background_label,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          keep_top_k=keep_top_k, nms_eta=nms_eta,
                          return_index=return_index)


class MultivariateNormalDiag:
    """fluid.layers.distributions.MultivariateNormalDiag: independent
    Normal per dim (diagonal covariance)."""

    def __init__(self, loc, scale):
        self.loc = loc._data if isinstance(loc, Tensor) else jnp.asarray(loc)
        sc = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)
        # reference passes a diagonal MATRIX; accept vector or matrix
        self.scale = jnp.diagonal(sc, axis1=-2, axis2=-1) if sc.ndim >= 2 \
            else sc

    def sample(self, shape=()):
        from ..framework.random import next_key

        z = jax.random.normal(next_key(),
                              tuple(shape) + self.loc.shape, jnp.float32)
        return Tensor(self.loc + z * self.scale)

    def entropy(self):
        d = self.loc.shape[-1]
        return Tensor(0.5 * d * (1.0 + np.log(2 * np.pi))
                      + jnp.sum(jnp.log(self.scale), axis=-1))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * jnp.sum(
            var_ratio + t1 - 1.0 - jnp.log(var_ratio), axis=-1))


def tensor_array_to_tensor(input, axis=1, use_stack=False):  # noqa: A002
    """Pairs with the array_write/create_array shims."""
    ts = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
          for t in input]
    out = jnp.stack(ts, axis=axis) if use_stack \
        else jnp.concatenate(ts, axis=axis)
    sizes = np.asarray([t.shape[axis] for t in ts] if not use_stack
                       else [1] * len(ts), np.int64)
    return Tensor(out), Tensor(jnp.asarray(sizes))


def random_crop(x, shape, seed=None):
    """random_crop_op: host-side random spatial crop (input pipeline)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    out_sh = list(shape)
    nd = arr.ndim
    starts = []
    rng = np.random.default_rng(seed)
    lead = nd - len(out_sh)
    for i, s in enumerate(out_sh):
        lim = arr.shape[lead + i] - s
        starts.append(rng.integers(0, lim + 1) if lim > 0 else 0)
    idx = tuple([builtins.slice(None)] * lead
                + [builtins.slice(st, st + s)
                   for st, s in zip(starts, out_sh)])
    return Tensor(jnp.asarray(arr[idx]))


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,  # noqa: A002
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """fluid hsigmoid: creates the tree weights and applies
    F.hsigmoid_loss (hierarchical_sigmoid_op)."""
    from ..framework.core import Parameter
    from ..nn import initializer as I

    D = int(input.shape[-1])
    rows = num_classes - 1 if not is_custom else int(
        np.asarray(getattr(path_table, "_data", path_table)).max()) + 1
    w = Parameter(I.XavierNormal()((rows, D), "float32"), name="hsig.w")
    b = Parameter(I.Constant(0.0)((rows,), "float32"), name="hsig.b")
    return _F.hsigmoid_loss(input, label, num_classes, w, bias=b,
                            path_table=path_table, path_code=path_code,
                            is_sparse=is_sparse)


# doc decorators the reference exposes (internal helpers, identity here)
def templatedoc(op_type=None):
    def deco(fn):
        return fn

    return deco


autodoc = templatedoc


def generate_layer_fn(op_type):
    raise NotImplementedError(
        "generate_layer_fn builds wrappers from the C++ OpProto registry; "
        "this framework has no OpProto — every op is a python function "
        "already present in this namespace")


generate_activation_fn = generate_layer_fn
generate_inplace_fn = generate_layer_fn


def _lod_refusal(name, replacement):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            "%s is LoD-coupled; per the README LoDTensor decision use the "
            "padded-dense equivalent: %s" % (name, replacement))

    fn.__name__ = name
    return fn


dynamic_lstm = _lod_refusal("dynamic_lstm", "paddle.nn.LSTM + lengths")
dynamic_lstmp = _lod_refusal("dynamic_lstmp", "paddle.nn.LSTM + projection")
dynamic_gru = _lod_refusal("dynamic_gru", "paddle.nn.GRU + lengths")
lod_reset = _lod_refusal("lod_reset", "sequence_pad/sequence_unpad")
lod_append = _lod_refusal("lod_append", "sequence_pad/sequence_unpad")
im2sequence = _lod_refusal("im2sequence", "unfold + reshape")
reorder_lod_tensor_by_rank = _lod_refusal(
    "reorder_lod_tensor_by_rank", "gather over a lengths argsort")
get_tensor_from_selected_rows = _lod_refusal(
    "get_tensor_from_selected_rows", "dense grads (SelectedRows decision)")
merge_selected_rows = _lod_refusal(
    "merge_selected_rows", "dense grads (SelectedRows decision)")
py_reader = _lod_refusal("py_reader", "paddle.io.DataLoader")
create_py_reader_by_data = _lod_refusal("create_py_reader_by_data",
                                        "paddle.io.DataLoader")
double_buffer = _lod_refusal("double_buffer",
                             "paddle.io.DataLoader (prefetches natively)")
load = _lod_refusal("load", "paddle.static.load / framework.io.load")


def _decode_refusal(name):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            "%s (op-level beam search) is replaced by the compiled decoder: "
            "fluid.layers.BeamSearchDecoder + dynamic_decode "
            "(paddle_tpu.nn.decode)" % name)

    fn.__name__ = name
    return fn


beam_search = _decode_refusal("beam_search")
beam_search_decode = _decode_refusal("beam_search_decode")
DynamicRNN = _decode_refusal("DynamicRNN")
StaticRNN = _decode_refusal("StaticRNN")
Decoder = _decode_refusal("Decoder")
BasicDecoder = _decode_refusal("BasicDecoder")
DecodeHelper = _decode_refusal("DecodeHelper")
TrainingHelper = _decode_refusal("TrainingHelper")
GreedyEmbeddingHelper = _decode_refusal("GreedyEmbeddingHelper")
SampleEmbeddingHelper = _decode_refusal("SampleEmbeddingHelper")


def _det_refusal(name, parts):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            "%s: compose from the implemented detection primitives (%s) — "
            "the reference op is this composition fused in C++" % (name, parts))

    fn.__name__ = name
    return fn


from ..vision.ops import ssd_loss, target_assign  # noqa: F401,E402
from ..vision.ops import (  # noqa: F401,E402
    retinanet_target_assign, rpn_target_assign,
)
from ..vision.ops import retinanet_detection_output  # noqa: F401,E402
from ..vision.ops import (  # noqa: F401,E402
    locality_aware_nms, polygon_box_transform,
)
box_decoder_and_assign = _det_refusal("box_decoder_and_assign",
                                      "box_coder + argmax gather")
roi_perspective_transform = _det_refusal("roi_perspective_transform",
                                         "grid_sampler + affine_grid")
deformable_roi_pooling = _det_refusal("deformable_roi_pooling",
                                      "deform_conv2d + roi_align")
from ..vision.ops import generate_proposal_labels  # noqa: F401,E402
generate_mask_labels = _det_refusal("generate_mask_labels",
                                    "roi_align over gt masks")
from ..vision.ops import density_prior_box  # noqa: F401,E402


def _ps_refusal(name):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            "%s belongs to the parameter-server/rec-sys stack the README "
            "documents out of the TPU critical path" % name)

    fn.__name__ = name
    return fn


continuous_value_model = _ps_refusal("continuous_value_model")
filter_by_instag = _ps_refusal("filter_by_instag")
hash = _ps_refusal("hash")  # noqa: A001


def similarity_focus(input, axis, indexes, name=None):  # noqa: A002
    raise NotImplementedError(
        "similarity_focus: compose from argmax + one-hot scatter masks; "
        "the reference op is that composition fused")


def inplace_abn(input, **kwargs):  # noqa: A002
    raise NotImplementedError(
        "inplace_abn exists to reuse the activation buffer in-place — a "
        "memory optimization XLA's buffer assignment performs on the "
        "plain batch_norm(act=...) composition; use that")


_center_registry = {}


def center_loss(input, label, num_classes, alpha, param_attr=None,  # noqa: A002
                update_center=True):
    """center_loss_op: 0.5*||x - c_y||^2 with RUNNING class centers:
    the centers live in a per-(name, shape) registry so every call of the
    training loop updates the same buffer, like the reference's
    persistable centers parameter."""
    from ..framework.core import Parameter
    from ..framework.param_attr import ParamAttr
    from ..nn import initializer as I

    D = int(input.shape[-1])
    attr = ParamAttr._to_attr(param_attr)
    cname = (attr.name if attr is not None and attr.name
             else "center_loss.centers")
    key = (cname, int(num_classes), D)
    centers = _center_registry.get(key)
    if centers is None:
        centers = Parameter(
            I.Constant(0.0)((int(num_classes), D), "float32"),
            name=cname, trainable=False)
        _center_registry[key] = centers

    def _impl(x, lab, c):
        lab = lab.reshape(-1).astype(jnp.int32)
        diff = x - c[lab]
        return 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)

    loss = apply_op(_impl, input, label, centers, op_name="center_loss")
    if update_center and not _is_tracer(getattr(input, "_data", input)):
        x = np.asarray(getattr(input, "_data", input))
        lab = np.asarray(getattr(label, "_data", label)).reshape(-1)
        c = np.asarray(centers._data)
        a = (alpha._data if isinstance(alpha, Tensor)
             else alpha)
        a = float(np.asarray(a).reshape(-1)[0])
        for cls in np.unique(lab):
            rows = x[lab == cls]
            resid = c[cls] - rows.mean(0)
            c = c.copy()
            c[cls] -= a * resid * len(rows) / (1.0 + len(rows))
        centers.set_value(c)
    return loss


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None):
    """chunk_eval_op: chunk extraction P/R/F1 for IOB/IOE/IOBES tagging.
    Host-side metric (eager), like the reference CPU-only kernel."""
    schemes = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}
    if chunk_scheme not in schemes:
        raise ValueError("chunk_scheme must be IOB/IOE/IOBES/plain")
    n_tag = schemes[chunk_scheme]
    excluded = set(excluded_chunk_types or [])

    def extract(seq):
        chunks, start, ctype = [], None, None
        for i, t in enumerate(seq):
            t = int(t)
            if t == num_chunk_types * n_tag:  # outside tag
                if start is not None:
                    chunks.append((start, i, ctype))
                    start = None
                continue
            # reference encoding: tag = label % num_tag_types,
            # type = label // num_tag_types (chunk_eval_op.h)
            pos, typ = t % n_tag, t // n_tag
            begin = (pos == 0) if chunk_scheme in ("IOB", "IOBES")                 else (start is None)
            if chunk_scheme == "IOBES" and pos == 3:   # S = single
                if start is not None:
                    chunks.append((start, i, ctype))
                    start = None
                chunks.append((i, i + 1, typ))
                continue
            if begin or typ != ctype:
                if start is not None:
                    chunks.append((start, i, ctype))
                start, ctype = i, typ
            # IOE tags: I=0, E=1; IOBES: B=0, I=1, E=2, S=3
            end_here = (chunk_scheme == "IOE" and pos == 1) or (
                chunk_scheme == "IOBES" and pos == 2)
            if end_here and start is not None:
                chunks.append((start, i + 1, ctype))
                start = None
        if start is not None:
            chunks.append((start, len(seq), ctype))
        return {c for c in chunks if c[2] not in excluded}

    pred = np.asarray(getattr(input, "_data", input))
    lab = np.asarray(getattr(label, "_data", label))
    if pred.ndim == 1:
        pred, lab = pred[None], lab[None]
    lens = (np.asarray(getattr(seq_length, "_data", seq_length)).reshape(-1)
            if seq_length is not None
            else np.full((pred.shape[0],), pred.shape[-1]))
    n_inf = n_lab = n_correct = 0
    for b in builtins.range(pred.shape[0]):
        L = int(lens[b])
        pc = extract(pred[b].reshape(-1)[:L])
        lc = extract(lab[b].reshape(-1)[:L])
        n_inf += len(pc)
        n_lab += len(lc)
        n_correct += len(pc & lc)
    precision = n_correct / n_inf if n_inf else 0.0
    recall = n_correct / n_lab if n_lab else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    mk = lambda v, dt=jnp.float32: Tensor(jnp.asarray(v, dt))  # noqa: E731
    return (mk(precision), mk(recall), mk(f1),
            mk(n_inf, jnp.int32), mk(n_lab, jnp.int32),
            mk(n_correct, jnp.int32))
