"""fluid.dygraph compatibility (reference python/paddle/fluid/dygraph/)."""
from __future__ import annotations

import contextlib

import numpy as np

from ..framework.core import Tensor, grad, no_grad  # noqa: F401
from ..nn import (  # noqa: F401
    BatchNorm, Dropout, GroupNorm, InstanceNorm2D, Layer,
    LayerList, LayerNorm, ParameterList, Sequential, SpectralNorm,
)
from ..nn import Embedding as _Embedding2
from ..nn import Linear as _Linear2
from ..nn import Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from ..nn import DataParallel  # noqa: F401
from ..distributed import ParallelEnv  # noqa: F401
from ..jit import ProgramTranslator, TracedLayer, to_static  # noqa: F401
from ..optimizer.lr import LRScheduler as LearningRateDecay  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """fluid.dygraph.guard: eager IS the default mode here; the guard
    only ensures static mode is off within the block."""
    from .. import disable_static, enable_static
    from ..static import _static_mode

    was_static = _static_mode[0]
    disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    from ..framework.core import to_tensor

    return to_tensor(np.asarray(value), dtype=dtype, stop_gradient=True)


def enabled():
    from .. import in_dynamic_mode

    return in_dynamic_mode()


def save_dygraph(state_dict, model_path):
    """Suffix rule mirrors the reference (dygraph/checkpoint.py): a dict
    containing Parameters is the model (.pdparams); anything else —
    optimizer slots, empty SGD state — is .pdopt, so saving both under
    one prefix never clobbers the weights."""
    from ..framework.core import Parameter
    from ..framework.io import save

    is_params = any(isinstance(v, Parameter) for v in state_dict.values())
    save(state_dict, model_path + (".pdparams" if is_params else ".pdopt"))


def load_dygraph(model_path):
    import os

    from ..framework.io import load

    params = load(model_path + ".pdparams") \
        if os.path.exists(model_path + ".pdparams") else None
    opt = load(model_path + ".pdopt") \
        if os.path.exists(model_path + ".pdopt") else None
    return params, opt


class Linear(_Linear2):
    """fluid.dygraph.Linear(input_dim, output_dim, param_attr, bias_attr,
    act, dtype) — the 1.x signature carries an activation."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class Embedding(_Embedding2):
    """fluid.dygraph.Embedding(size=[vocab, dim], ...) — 1.x passes the
    table shape as one list."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(int(size[0]), int(size[1]),
                         padding_idx=padding_idx, sparse=is_sparse,
                         weight_attr=param_attr)


# -- remaining 1.x dygraph names ------------------------------------------

from ..framework.core import Parameter  # noqa: E402
from ..jit import TranslatedLayer, not_to_static  # noqa: F401,E402
from ..jit import set_code_level, set_verbosity  # noqa: F401,E402
from ..jit import to_static as declarative  # noqa: F401,E402
from ..jit import to_static as dygraph_to_static_func  # noqa: F401,E402
from ..nn import Conv3DTranspose, GRUCell, LSTMCell  # noqa: F401,E402
from ..framework.core import no_grad as no_grad_  # noqa: F401,E402
from ..framework.io import save, load  # noqa: F401,E402
from ..optimizer.lr import (  # noqa: F401,E402
    CosineAnnealingDecay as CosineDecay, ExponentialDecay,
    InverseTimeDecay, LambdaDecay, LinearWarmup as LinearLrWarmup,
    MultiStepDecay, NaturalExpDecay, NoamDecay, PiecewiseDecay,
    PolynomialDecay, ReduceOnPlateau as ReduceLROnPlateau, StepDecay,
)


def enable_dygraph(place=None):
    from .. import disable_static

    disable_static()


def disable_dygraph():
    from .. import enable_static

    enable_static()


def prepare_context(strategy=None):
    """1.x DataParallel bootstrap; the mesh runtime needs no context
    object — init_parallel_env covers it."""
    from ..distributed import init_parallel_env

    init_parallel_env()
    return None


def start_gperf_profiler():
    from ..profiler import start_profiler

    start_profiler()


def stop_gperf_profiler():
    from ..profiler import stop_profiler

    stop_profiler()


class Pool2D(Layer):
    """1.x Pool2D layer over the pooling functionals."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self._args = (pool_size, pool_type, pool_stride, pool_padding,
                      global_pooling, ceil_mode, exclusive, data_format)

    def forward(self, x):
        from .layers import pool2d

        size, ptype, stride, pad, gp, ceil, excl, df = self._args
        return pool2d(x, size, ptype, stride, pad, gp, ceil, excl,
                      data_format=df)


class Flatten(Layer):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from .layers import flatten

        return flatten(x, self.axis)


class InstanceNorm(InstanceNorm2D):
    """1.x name for InstanceNorm2D."""


class PRelu(Layer):
    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [int(channel)]
        else:
            shape = [int(s) for s in input_shape[1:]]
        self.weight = self.create_parameter(shape=shape, attr=param_attr,
                                            is_bias=False)
        from ..nn import initializer as I

        if param_attr is None or getattr(param_attr, "initializer",
                                         None) is None:
            self.weight.set_value(I.Constant(0.25)(tuple(shape), "float32"))

    def forward(self, x):
        from ..nn import functional as F

        return F.prelu(x, self.weight)


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        from ..nn import initializer as I

        self.weight = self.create_parameter(
            shape=[output_dim, input1_dim, input2_dim], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(shape=[output_dim],
                                          attr=bias_attr, is_bias=True)
        self._act = act

    def forward(self, x, y):
        import jax.numpy as jnp

        from ..framework.core import apply_op

        def _btp(x, y, w, b):
            return jnp.einsum("bd,kde,be->bk", x, w, y) + b

        out = apply_op(_btp, x, y, self.weight, self.bias,
                       op_name="bilinear_tensor_product")
        if self._act:
            from ..nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class NCE(Layer):
    """1.x NCE layer (nce_op): owns the class weights; uniform sampler."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False):
        super().__init__()
        from ..nn import initializer as I

        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.seed = seed
        self.weight = self.create_parameter(
            shape=[num_total_classes, dim], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(shape=[num_total_classes],
                                          attr=bias_attr, is_bias=True)

    def forward(self, input, label, sample_weight=None):  # noqa: A002
        import jax
        import jax.numpy as jnp

        from ..framework.core import Tensor, apply_op
        from ..framework.random import next_key

        if self.seed:
            # deterministic but ADVANCING stream: fold a call counter in,
            # like static.nn.nce (a fixed key would freeze the negatives)
            self._calls = getattr(self, "_calls", 0) + 1
            key = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                     self._calls)
        else:
            key = next_key()

        def _nce(x, lab, w, b, key, num_neg_samples, num_total_classes):
            neg = jax.random.randint(key, (num_neg_samples,), 0,
                                     num_total_classes)
            lab = lab.reshape(-1)
            pos_logit = jnp.sum(x * w[lab], -1) + b[lab]
            neg_logit = x @ w[neg].T + b[neg]
            log_noise = jnp.log(jnp.asarray(
                num_neg_samples / num_total_classes, x.dtype))
            pos = jax.nn.softplus(-(pos_logit - log_noise))
            negl = jax.nn.softplus(neg_logit - log_noise)
            return (pos + jnp.sum(negl, -1))[:, None]

        return apply_op(_nce, input, label, self.weight, self.bias,
                        Tensor(key),
                        num_neg_samples=int(self.num_neg_samples),
                        num_total_classes=int(self.num_total_classes),
                        op_name="nce")


class GRUUnit(Layer):
    """1.x GRUUnit (gru_unit_op.h): input is the pre-projected [B, 3H]
    gate vector; owns the hidden-to-gate weight [H, 3H]. Returns
    (hidden, reset_hidden_pre = r*h_prev, gate = [u, r, c~] of width 3H)
    — the reference's three-output contract."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        from ..nn import initializer as I

        self._hidden = size // 3
        self._origin_mode = origin_mode
        self.weight = self.create_parameter(
            shape=[self._hidden, size], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(shape=[size], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, hidden):  # noqa: A002
        import jax
        import jax.numpy as jnp

        from ..framework.core import apply_op

        def _gru(x, h, w, b, H, origin_mode):
            g = x + b
            ur = jax.nn.sigmoid(g[:, : 2 * H] + h @ w[:, : 2 * H])
            u, r = ur[:, :H], ur[:, H:]
            rh = r * h
            c = jnp.tanh(g[:, 2 * H:] + rh @ w[:, 2 * H:])
            new_h = (u * h + (1 - u) * c) if origin_mode                 else ((1 - u) * h + u * c)
            gate = jnp.concatenate([u, r, c], axis=1)
            return new_h, rh, gate

        return apply_op(_gru, input, hidden, self.weight, self.bias,
                        H=self._hidden, origin_mode=self._origin_mode,
                        op_name="gru_unit")


class TreeConv(Layer):
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "TreeConv (tree_conv_op) consumes LoD edge sets; per the README "
            "LoD decision express tree convolution as gather + conv over "
            "padded adjacency")
