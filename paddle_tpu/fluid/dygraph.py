"""fluid.dygraph compatibility (reference python/paddle/fluid/dygraph/)."""
from __future__ import annotations

import contextlib

import numpy as np

from ..framework.core import Tensor, grad, no_grad  # noqa: F401
from ..nn import (  # noqa: F401
    BatchNorm, Dropout, GroupNorm, InstanceNorm2D, Layer,
    LayerList, LayerNorm, ParameterList, Sequential, SpectralNorm,
)
from ..nn import Embedding as _Embedding2
from ..nn import Linear as _Linear2
from ..nn import Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from ..nn import DataParallel  # noqa: F401
from ..distributed import ParallelEnv  # noqa: F401
from ..jit import ProgramTranslator, TracedLayer, to_static  # noqa: F401
from ..optimizer.lr import LRScheduler as LearningRateDecay  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """fluid.dygraph.guard: eager IS the default mode here; the guard
    only ensures static mode is off within the block."""
    from .. import disable_static, enable_static
    from ..static import _static_mode

    was_static = _static_mode[0]
    disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    from ..framework.core import to_tensor

    return to_tensor(np.asarray(value), dtype=dtype, stop_gradient=True)


def enabled():
    from .. import in_dynamic_mode

    return in_dynamic_mode()


def save_dygraph(state_dict, model_path):
    """Suffix rule mirrors the reference (dygraph/checkpoint.py): a dict
    containing Parameters is the model (.pdparams); anything else —
    optimizer slots, empty SGD state — is .pdopt, so saving both under
    one prefix never clobbers the weights."""
    from ..framework.core import Parameter
    from ..framework.io import save

    is_params = any(isinstance(v, Parameter) for v in state_dict.values())
    save(state_dict, model_path + (".pdparams" if is_params else ".pdopt"))


def load_dygraph(model_path):
    import os

    from ..framework.io import load

    params = load(model_path + ".pdparams") \
        if os.path.exists(model_path + ".pdparams") else None
    opt = load(model_path + ".pdopt") \
        if os.path.exists(model_path + ".pdopt") else None
    return params, opt


class Linear(_Linear2):
    """fluid.dygraph.Linear(input_dim, output_dim, param_attr, bias_attr,
    act, dtype) — the 1.x signature carries an activation."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class Embedding(_Embedding2):
    """fluid.dygraph.Embedding(size=[vocab, dim], ...) — 1.x passes the
    table shape as one list."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(int(size[0]), int(size[1]),
                         padding_idx=padding_idx, sparse=is_sparse,
                         weight_attr=param_attr)
