"""paddle.fluid compatibility namespace.

The reference is ~v2.1, where most user scripts (and all of its own unit
tests) still import ``paddle.fluid``. This shim lets those scripts run
with only the top-level import rename: every name here re-exports or
thinly adapts the 2.x surface this framework implements natively —
nothing is re-implemented (see the README "fluid.layers legacy wrapper
surface" section for the policy).
"""
from __future__ import annotations

import contextlib

from ..framework.core import Parameter, Tensor  # noqa: F401
from ..framework.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from ..device import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace, NPUPlace,
    is_compiled_with_cuda,
)
from ..static import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, Executor,
    ParallelExecutor, Program, Scope, default_main_program,
    default_startup_program, global_scope, name_scope, program_guard,
    scope_guard,
)
from ..framework.io import save, load  # noqa: F401

from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import optimizer  # noqa: F401
from . import initializer  # noqa: F401
from . import regularizer  # noqa: F401
from . import io  # noqa: F401
from . import backward  # noqa: F401
from . import clip  # noqa: F401
from .framework import Variable  # noqa: F401
from . import framework  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from . import profiler  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data → static.data (reference fluid/data.py)."""
    from ..static import data as _data

    return _data(name, shape, dtype, lod_level)


from ..static.nn import embedding  # noqa: F401,E402


def enable_dygraph(place=None):
    from .. import disable_static

    disable_static()


def disable_dygraph():
    from .. import enable_static

    enable_static()


def enable_imperative(place=None):
    enable_dygraph(place)


def disable_imperative():
    disable_dygraph()


def require_version(min_version, max_version=None):
    from ..utils import require_version as _rv

    return _rv(min_version, max_version)


def set_flags(flags):
    from .. import set_flags as _sf

    _sf(flags)


def get_flags(flags):
    from .. import get_flags as _gf

    return _gf(flags)


from .framework import in_dygraph_mode  # noqa: F401,E402


@contextlib.contextmanager
def device_guard(device=None):
    from ..static import device_guard as _dg

    with _dg(device):
        yield
