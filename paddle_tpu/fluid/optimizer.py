"""fluid.optimizer compatibility: the 1.x *Optimizer class names
(reference python/paddle/fluid/optimizer.py)."""
from ..optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, DecayedAdagrad, Dpsgd, Ftrl,
    Lamb, LarsMomentum, Momentum, RMSProp, SGD, ExponentialMovingAverage,
)
from ..incubate import LookAhead as LookaheadOptimizer  # noqa: F401
from ..incubate import ModelAverage  # noqa: F401

SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DpsgdOptimizer = Dpsgd
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
