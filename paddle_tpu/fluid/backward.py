"""fluid.backward compatibility (reference fluid/backward.py)."""
from ..static import append_backward, gradients  # noqa: F401


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    return gradients(targets, inputs, target_gradients, no_grad_set)
