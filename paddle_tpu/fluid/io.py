"""fluid.io compatibility (reference fluid/io.py): the 1.x dirname-based
save/load_inference_model conventions over the 2.x artifact format."""
from __future__ import annotations

import os

from ..framework.io import load, save  # noqa: F401


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kwargs):
    from ..static import default_main_program
    from ..static import save_inference_model as _save

    prog = main_program or default_main_program()
    # 1.x passes feed NAMES; resolve them against the program's recorded
    # feed placeholders (static.data registers into prog.feed_vars)
    feeds = []
    for n in feeded_var_names:
        if isinstance(n, str):
            if n not in prog.feed_vars:
                raise KeyError(
                    "save_inference_model: feed name %r is not a "
                    "fluid.data placeholder of this program" % (n,))
            feeds.append(prog.feed_vars[n])
        else:
            feeds.append(n)
    os.makedirs(dirname, exist_ok=True)
    _save(os.path.join(dirname, "model"), feeds, target_vars, executor,
          program=prog)
    return feeded_var_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, **kwargs):
    from ..static import load_inference_model as _load

    return _load(os.path.join(dirname, "model"), executor)


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program
    from ..static import save as _save

    os.makedirs(dirname, exist_ok=True)
    _save(main_program or default_main_program(),
          os.path.join(dirname, "persist"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program
    from ..static import load as _loadp

    _loadp(main_program or default_main_program(),
           os.path.join(dirname, "persist"))
