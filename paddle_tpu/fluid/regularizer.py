"""fluid.regularizer compatibility."""
from ..regularizer import L1Decay, L2Decay  # noqa: F401

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
