"""fluid.data_feeder compatibility (reference fluid/data_feeder.py)."""
from __future__ import annotations

import numpy as np

from ..framework import dtype as dtypes
from ..tensor.creation import check_shape  # noqa: F401


def convert_dtype(dtype):
    d = dtypes.convert_dtype(dtype)
    return np.dtype(d).name


def check_variable_and_dtype(input, input_name, expected_dtype, op_name,  # noqa: A002
                             extra_message=""):
    check_dtype(input.dtype, input_name, expected_dtype, op_name,
                extra_message)


def check_dtype(input_dtype, input_name, expected_dtype, op_name,
                extra_message=""):
    got = np.dtype(input_dtype).name if input_dtype is not None else None
    if got not in tuple(expected_dtype):
        raise TypeError(
            "%s: %s dtype must be one of %s, got %s. %s"
            % (op_name, input_name, expected_dtype, got, extra_message))


def check_type(input, input_name, expected_type, op_name):  # noqa: A002
    if not isinstance(input, expected_type):
        raise TypeError("%s: %s must be %s, got %s"
                        % (op_name, input_name, expected_type, type(input)))


class DataFeeder:
    """Minimal feeder: list of samples → feed dict of batched arrays
    (reference DataFeeder.feed)."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = [getattr(v, "name", v) for v in feed_list]

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, name in enumerate(self.feed_names):
            out[name] = np.stack([np.asarray(r[i]) for r in rows])
        return out
