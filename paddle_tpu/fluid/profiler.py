"""fluid.profiler compatibility (reference fluid/profiler.py)."""
from ..profiler import (  # noqa: F401
    cuda_profiler, npu_profiler, profiler, reset_profiler, start_profiler,
    stop_profiler,
)
