"""paddle_tpu.static — the static-graph world.

Parity: the reference's Program/Executor stack (framework.py:4393
``Program``, executor.py:1065 ``Executor.run``, backward.py:1406
``append_backward``, optimizer minimize on programs). TPU-native design:
user code builds the graph by calling ordinary ops on symbolic placeholders
(static/graph.py records through the SAME apply_op funnel eager mode uses),
and ``Executor.run`` replays the recorded DAG as ONE jitted XLA program per
(fetch set, feed shapes) — the ProgramDesc interpreter loop (reference
executor.cc:490 op-by-op hot loop) collapses into a single compiled module.

Typical reference workflow that runs unchanged::

    paddle.enable_static()
    x = paddle.static.data("x", [-1, 784])
    y = paddle.static.data("y", [-1, 1], dtype="int64")
    logits = my_layer(x)                    # any eager layers/ops
    loss = F.cross_entropy(logits, y)
    opt = paddle.optimizer.SGD(0.01, parameters=my_layer.parameters())
    opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    loss_val, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from ..framework.param_attr import ParamAttr
from ..jit import InputSpec  # noqa: F401
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401
from . import nn  # noqa: F401
from .graph import (  # noqa: F401
    OpRecord, SymbolicTensor, SymExpr, collect_leaves, evaluate_exprs,
)

__all__ = [
    "InputSpec", "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "CompiledProgram",
    "name_scope", "device_guard", "py_func", "save_inference_model",
    "load_inference_model", "gradients", "append_backward", "nn",
    "cond", "while_loop", "BuildStrategy", "ExecutionStrategy", "ParallelEnv",
    "Block", "Operator", "Variable", "ExponentialMovingAverage",
    "ParallelExecutor", "Print", "WeightNormParamAttr", "accuracy", "auc",
    "cpu_places", "cuda_places", "xpu_places", "npu_places", "Scope",
    "create_global_var", "create_parameter", "global_scope", "scope_guard",
    "load", "save", "load_from_file", "save_to_file", "load_program_state",
    "set_program_state", "normalize_program", "serialize_program",
    "serialize_persistables", "deserialize_program",
    "deserialize_persistables",
]

_static_mode = [False]


class Operator:
    """Introspection view over one recorded op (reference framework.py
    Operator: .type, .input_arg_names, .output_arg_names, .attr)."""

    def __init__(self, block: "Block", rec: OpRecord, idx: int):
        self._block = block
        self._rec = rec
        self.idx = idx

    @property
    def type(self):  # noqa: A003
        return self._rec.name

    @property
    def input_arg_names(self) -> List[str]:
        names = []
        for a in self._rec.args:
            if isinstance(a, SymExpr):
                names.append(self._block._name_of_expr(a))
            elif isinstance(a, Tensor):
                names.append(a.name or f"tensor_{id(a)}")
        return names

    @property
    def output_arg_names(self) -> List[str]:
        return [self._block._op_output_name(self._rec, k)
                for k in range(self._rec.n_outputs)]

    def attr(self, name: str):
        return self._rec.attrs.get(name)

    def all_attrs(self) -> Dict[str, object]:
        return dict(self._rec.attrs)

    @property
    def attr_names(self) -> List[str]:
        return list(self._rec.attrs)

    def __repr__(self):
        ins = ", ".join(self.input_arg_names)
        outs = ", ".join(self.output_arg_names)
        return f"{{{outs}}} = {self.type}(inputs=[{ins}], **{self.all_attrs()})"


class Variable:
    """Introspection view over a program value (reference framework.py
    Variable: .name/.shape/.dtype/.persistable)."""

    def __init__(self, name, shape, dtype, persistable=False, tensor=None):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self.persistable = persistable
        self._tensor = tensor

    def __repr__(self):
        kind = "persist " if self.persistable else ""
        return f"var {self.name} : {kind}{self.shape} {self.dtype}"


class Block:
    """Introspection view over a Program's op list (reference framework.py
    Block). The TPU program is a flat DAG — control flow lives inside
    traced lax.cond/while bodies, not nested blocks — so there is exactly
    one block, matching the reference's global block for the same code."""

    def __init__(self, program: "Program", idx: int = 0):
        self.program = program
        self.idx = idx

    # -- naming --------------------------------------------------------------
    def _op_output_name(self, rec: OpRecord, index: int) -> str:
        i = self.program.ops.index(rec)
        suffix = f".{index}" if rec.n_outputs > 1 else ""
        return f"{rec.name}_{i}.tmp_0{suffix}"

    def _name_of_expr(self, e: SymExpr) -> str:
        if e.kind == "feed":
            return e.name
        if e.kind == "tensor":
            return e.tensor.name or f"tensor_{id(e.tensor)}"
        return self._op_output_name(e.op, e.index)

    # -- reference surface ---------------------------------------------------
    @property
    def ops(self) -> List[Operator]:
        return [Operator(self, rec, i)
                for i, rec in enumerate(self.program.ops)]

    @property
    def vars(self) -> Dict[str, Variable]:
        out = {}
        for name, t in self.program.feed_vars.items():
            out[name] = Variable(name, t._data.shape, str(t._data.dtype))
        for p in self.program.all_parameters():
            n = p.name or f"tensor_{id(p)}"
            out[n] = Variable(n, p._data.shape, str(p._data.dtype),
                              persistable=True, tensor=p)
        for rec in self.program.ops:
            for k in range(rec.n_outputs):
                n = self._op_output_name(rec, k)
                out[n] = Variable(n, (), "unknown")
        return out

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            from ..framework.enforce import NotFoundError

            raise NotFoundError(f"Variable {name!r} is not found in block "
                                f"{self.idx}.")
        return v

    def __repr__(self):
        lines = [f"block {self.idx} {{"]
        for v in self.vars.values():
            lines.append(f"  {v!r}")
        for op in self.ops:
            lines.append(f"  {op!r}")
        lines.append("}")
        return "\n".join(lines)


class Program:
    """A recorded op DAG + feed placeholders + training directives."""

    def __init__(self):
        self.feed_vars: Dict[str, SymbolicTensor] = {}
        self.feed_dynamic: Dict[str, List[int]] = {}  # name -> -1 dim indices
        self.ops: List[OpRecord] = []
        self.train_specs: List[tuple] = []   # (optimizer, loss SymbolicTensor)
        self.random_seed = None

    def global_block(self) -> Block:
        return Block(self, 0)

    def block(self, index: int) -> Block:
        if index != 0:
            from ..framework.enforce import OutOfRangeError

            raise OutOfRangeError(
                f"Program has 1 block (the flat DAG; control flow is traced "
                f"into op bodies), block({index}) does not exist.")
        return Block(self, 0)

    def current_block(self) -> Block:
        return Block(self, 0)

    @property
    def num_blocks(self) -> int:
        return 1

    @property
    def blocks(self) -> List[Block]:
        return [Block(self, 0)]

    def list_vars(self) -> List["Variable"]:
        return list(self.global_block().vars.values())

    def all_parameters(self):
        exprs = [t._expr for t in self.feed_vars.values()]
        exprs += [loss._expr for _, loss in self.train_specs]
        _, tensors = collect_leaves(
            [SymExpr("op", op=op, index=0) for op in self.ops] + exprs)
        return [t for t in tensors if isinstance(t, Parameter)]

    def clone(self, for_test=False):
        p = Program()
        p.feed_vars = dict(self.feed_vars)
        p.ops = list(self.ops)
        p.train_specs = [] if for_test else list(self.train_specs)
        p.random_seed = self.random_seed
        return p

    def to_string(self, throw_on_error=False, with_details=False) -> str:
        return repr(self.global_block())

    def __str__(self):
        return self.to_string()

    def __repr__(self):
        return (f"Program(feeds={list(self.feed_vars)}, ops={len(self.ops)}, "
                f"train_specs={len(self.train_specs)})")


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


def _on_op_recorded(rec: OpRecord):
    rec.program = _default_main[0]
    _default_main[0].ops.append(rec)


@contextmanager
def program_guard(main_program, startup_program=None):
    pm, ps = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = pm, ps


class FeedTensor(SymbolicTensor):
    """Feed placeholder: ``.shape`` reports -1 for runtime-determined dims
    (reference Variable semantics) instead of a baked build-time constant;
    internal shape inference uses 1 and the executor retraces per concrete
    feed shape."""

    __slots__ = ("_orig_shape",)

    def __init__(self, expr, aval, orig_shape):
        super().__init__(expr, aval)
        self._orig_shape = tuple(orig_shape)

    @property
    def shape(self):
        return list(self._orig_shape)


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference paddle.static.data). dim -1/None means
    runtime-determined: reported as -1 in ``.shape``, exported as a
    symbolic dimension by save_inference_model."""
    from ..framework import dtype as dtypes

    dt = dtypes.convert_dtype(dtype)
    orig = tuple(-1 if (s is None or int(s) < 0) else int(s) for s in shape)
    build = tuple(1 if s == -1 else s for s in orig)
    aval = jax.ShapeDtypeStruct(build, dt)
    t = FeedTensor(SymExpr("feed", name=name, aval=aval), aval, orig)
    t.name = name
    prog = default_main_program()
    prog.feed_vars[name] = t
    prog.feed_dynamic[name] = [i for i, s in enumerate(orig) if s == -1]
    return t


@contextmanager
def name_scope(prefix):
    yield


@contextmanager
def device_guard(device=None):
    """Pipeline-stage placement hint (reference framework.py device_guard);
    stage placement in the TPU build is declared via mesh shardings, so
    this is accepted and ignored."""
    yield


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op; see static.nn.py_func (jax.pure_callback)."""
    from .nn import py_func as _py_func

    return _py_func(func, x, out, backward_func, skip_vars_in_backward_input)


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Static autodiff (reference backward.py:1406). Returns
    [(param, grad_symbol)] — grads become fetchable symbols."""
    if not isinstance(loss, SymbolicTensor):
        raise TypeError("append_backward expects a symbolic loss")
    params = parameter_list or _params_for(loss)
    grad_op = OpRecord(_GradFn(loss, params), [loss._expr], {}, "grad")
    grad_op.n_outputs = len(params)
    out = []
    for i, p in enumerate(params):
        aval = jax.ShapeDtypeStruct(tuple(p._data.shape), p._data.dtype)
        g = SymbolicTensor(SymExpr("op", op=grad_op, index=i, aval=aval), aval)
        g.name = (p.name or f"param{i}") + "@GRAD"
        out.append((p, g))
    return out


class _GradFn:
    """Env-aware op body: dloss/dparams by replaying the loss subgraph
    under jax.grad with the params as traced inputs (XLA CSEs the
    duplicated forward away inside the one jitted replay)."""

    __name__ = "grad"

    def __init__(self, loss, params):
        self.loss_expr = loss._expr
        self.params = params

    def evaluate_with_env(self, feed_env, tensor_env):
        from .graph import grad_of_loss

        return grad_of_loss(self.loss_expr, self.params, feed_env, tensor_env)


def _params_for(loss: SymbolicTensor):
    _, tensors = collect_leaves([loss._expr])
    return [t for t in tensors
            if isinstance(t, Parameter) and getattr(t, "trainable", True)
            and not t.stop_gradient]


class BuildStrategy:
    """reference details/build_strategy.h surface; knobs that map to XLA
    decisions are accepted and recorded (fusion/memory-optimize happen in
    the compiler), the rest are inert parity fields."""

    def __init__(self):
        self.reduce_strategy = "AllReduce"
        self.gradient_scale_strategy = "CoeffNumDevice"
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1


class CompiledProgram:
    """reference compiler.py CompiledProgram: program + build/exec strategy.

    ``with_data_parallel`` marks the program for batch-dim sharding over
    the "data" axis of the active mesh — the GSPMD replacement for the
    reference's per-device graph replication (multi_devices_graph_pass);
    Executor.run shards feeds accordingly when a mesh is active.
    """

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = None
        self._data_parallel = False
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self.build_strategy = build_strategy
        self.exec_strategy = exec_strategy
        return self


class ParallelEnv:
    """reference dygraph ParallelEnv: rank / world-size / device info from
    the distributed environment (fleet.init or the launcher's env)."""

    def __init__(self):
        from ..distributed import env as _env

        self._rank = _env.get_rank()
        st = _env.get_state()
        topo = st.get("topology")
        self._world_size = topo.world_size() if topo else int(
            __import__("os").environ.get("PADDLE_TRAINERS_NUM", "1"))

    @property
    def rank(self):
        return self._rank

    local_rank = rank

    @property
    def world_size(self):
        return self._world_size

    nranks = world_size

    @property
    def device_id(self):
        return self._rank

    @property
    def current_endpoint(self):
        import os

        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


class Executor:
    """Replays recorded programs as jitted XLA modules
    (reference executor.py:607 Executor / :1065 run)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}

    # -- internals ----------------------------------------------------------

    def _exec_fetches(self, fetch_exprs, feed_arrays, grads_of=None):
        """One jitted call: fetch values (+ optional grads wrt params).

        Returns (fetch_values, grads, params) where grads aligns with
        params (None when grads_of is None)."""
        from .graph import grad_of_loss

        feeds_needed, tensors = collect_leaves(fetch_exprs)
        # differentiate only trainable, unfrozen Parameters; frozen ones
        # ride along as plain captured tensors
        params = [t for t in tensors
                  if isinstance(t, Parameter) and not t.stop_gradient
                  and getattr(t, "trainable", True)]
        param_ids = {id(p) for p in params}
        other = [t for t in tensors if id(t) not in param_ids]
        key = (tuple((id(e.op), e.index) if e.kind == "op"
                     else (e.kind, e.name, id(e.tensor))
                     for e in fetch_exprs),
               tuple((k, tuple(np.shape(v))) for k, v in sorted(feed_arrays.items())),
               grads_of is not None)
        fn = self._cache.get(key)
        if fn is None:
            loss_expr = grads_of

            def pure(param_arrays, other_arrays, feed_env):
                tensor_env = {id(t): a for t, a in zip(params, param_arrays)}
                tensor_env.update({id(t): a for t, a in zip(other, other_arrays)})
                if loss_expr is not None:
                    grads = grad_of_loss(loss_expr, params, feed_env, tensor_env)
                else:
                    grads = None
                vals = evaluate_exprs(fetch_exprs, feed_env, tensor_env)
                return vals, grads

            fn = jax.jit(pure)
            self._cache[key] = fn
        param_arrays = [p._data for p in params]
        other_arrays = [t._data for t in other]
        vals, grads = fn(param_arrays, other_arrays, feed_arrays)
        return vals, grads, params

    # -- public -------------------------------------------------------------

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        program = program if program is not None else default_main_program()
        shard_feeds = False
        if isinstance(program, CompiledProgram):
            shard_feeds = program._data_parallel
            program = program.program
        if isinstance(program, InferenceProgram):
            vals = program.exported.run(feed or {})
            want = fetch_list or []
            out = [vals[f.index] if isinstance(f, _FetchHandle) else vals[int(f)]
                   for f in want] if want else vals
            if return_numpy:
                return [np.asarray(v) for v in out]
            return [Tensor(v) for v in out]
        if not isinstance(program, Program):
            raise TypeError(f"cannot run {type(program)}")
        if not program.ops and not program.train_specs and not fetch_list:
            return []  # startup program: params initialize eagerly

        feed = feed or {}
        feed_arrays = {
            k: (v._data if isinstance(v, Tensor) else np.asarray(v))
            for k, v in feed.items()
        }
        if shard_feeds:
            from ..parallel.mesh import get_mesh

            mesh = get_mesh()
            if mesh is not None and "data" in mesh.axis_names:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as PSpec

                sh = NamedSharding(mesh, PSpec("data"))
                feed_arrays = {
                    k: jax.device_put(v, sh)
                    if getattr(v, "ndim", 0) >= 1
                    and v.shape[0] % mesh.shape["data"] == 0 else v
                    for k, v in feed_arrays.items()
                }
        fetch_list = fetch_list or []
        fetch_exprs = []
        for f in fetch_list:
            if isinstance(f, SymbolicTensor):
                fetch_exprs.append(f._expr)
            elif isinstance(f, str) and f in program.feed_vars:
                fetch_exprs.append(program.feed_vars[f]._expr)
            else:
                raise TypeError(f"cannot fetch {f!r}")

        # training directives run like the reference's optimizer ops at the
        # end of the program: grads of pre-update params, then update.
        # Multiple minimize() calls (e.g. GAN d/g) run sequentially, each
        # seeing the previous spec's updates; fetches evaluate with the
        # FIRST spec (pre-any-update), matching op order in the reference.
        fetch_vals = None
        for optimizer, loss in program.train_specs:
            want = fetch_exprs if fetch_vals is None else []
            vals, grads, params = self._exec_fetches(
                want + [loss._expr], feed_arrays, grads_of=loss._expr)
            if fetch_vals is None:
                fetch_vals = vals[:-1]
            grad_of = {id(p): g for p, g in zip(params, grads)}
            if optimizer._parameter_list is None:
                optimizer._parameter_list = list(params)
            for p in optimizer._parameter_list:
                if id(p) in grad_of:
                    p.grad = Tensor(grad_of[id(p)])
            optimizer.step()
            optimizer.clear_grad()
        if program.train_specs:
            if return_numpy:
                return [np.asarray(v) for v in fetch_vals]
            return [Tensor(v) for v in fetch_vals]

        if not fetch_exprs:
            return []
        vals, _, _ = self._exec_fetches(fetch_exprs, feed_arrays)
        if return_numpy:
            return [np.asarray(v) for v in vals]
        return [Tensor(v) for v in vals]

    def close(self):
        pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         legacy_format=False, program=None, **kwargs):
    """Serialize the inference graph (reference fluid/io.py
    save_inference_model).

    Default: versioned StableHLO artifact via jax.export (static/export.py
    — the TPU analog of the reference's ProgramDesc proto,
    framework.proto:234), loadable with zero model-building Python.
    ``legacy_format=True`` writes the round-2 cloudpickle closure instead
    (version-fragile; kept for migration)."""
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]

    if not legacy_format:
        from .export import export_fetches, write_artifacts

        prog = program or default_main_program()
        data_bytes, state, meta = export_fetches(
            feed_vars, fetch_vars, dynamic_dims=prog.feed_dynamic)
        write_artifacts(path_prefix, data_bytes, state, meta)
        return

    import pickle

    exprs = [t._expr for t in fetch_vars]
    feeds, tensors = collect_leaves(exprs)
    state = {f"__t{i}": np.asarray(t._data) for i, t in enumerate(tensors)}
    meta = {
        "feed_names": [t.name for t in feed_vars],
        "state": state,
    }
    from ..framework.io import save as _save

    _save(meta, path_prefix + ".pdiparams")
    # cloudpickle: op bodies are often closures/partials a plain pickle
    # cannot carry (the reference serializes a ProgramDesc proto instead;
    # our "program" IS the python closure DAG)
    import cloudpickle

    with open(path_prefix + ".pdmodel", "wb") as f:
        cloudpickle.dump(_ExportedProgram(exprs, tensors), f)


class _ExportedProgram:
    """Pickled closure of the fetch DAG; tensors are re-bound on load."""

    def __init__(self, exprs, tensors):
        # replace tensor leaves with indices for pickling
        self.n_tensors = len(tensors)
        idx = {id(t): i for i, t in enumerate(tensors)}
        self.exprs = [_strip(e, idx) for e in exprs]

    def bind(self, arrays):
        return [_rebind(e, arrays) for e in self.exprs]


def _strip(e, idx, memo=None):
    # memo keyed by id(OpRecord): sibling outputs of a multi-output op must
    # reference the SAME op tuple so pickling (and _rebind's dedup)
    # preserves the sharing and the op executes once after load
    memo = memo if memo is not None else {}
    if not isinstance(e, SymExpr):
        return e
    if e.kind == "tensor":
        return ("__tensor__", idx[id(e.tensor)])
    if e.kind == "feed":
        return ("__feed__", e.name)
    if id(e.op) not in memo:
        memo[id(e.op)] = ("__op__", e.op.fn,
                          tuple(_strip(a, idx, memo) for a in e.op.args),
                          tuple(sorted(e.op.attrs.items())), e.op.n_outputs)
    return ("__out__", memo[id(e.op)], e.index)


def _rebind(e, arrays, memo=None, op_memo=None):
    memo = memo if memo is not None else {}
    op_memo = op_memo if op_memo is not None else {}
    if not isinstance(e, tuple) or not e or not isinstance(e[0], str):
        return e
    if e[0] == "__tensor__":
        return SymExpr("tensor", tensor=Tensor(arrays[e[1]]))
    if e[0] == "__feed__":
        return SymExpr("feed", name=e[1])
    if e[0] == "__out__":
        _, op_t, index = e
        key = id(op_t)
        if key not in op_memo:
            _, fn, args, attrs, n_out = op_t
            rec = OpRecord(fn, [ _rebind(a, arrays, memo, op_memo) for a in args],
                           dict(attrs), getattr(fn, "__name__", "op"))
            rec.n_outputs = n_out
            op_memo[key] = rec
        return SymExpr("op", op=op_memo[key], index=index)
    return e


class InferenceProgram(Program):
    """Loaded StableHLO inference artifact; Executor.run executes it
    directly (no symbolic replay — the program is already compiled IR)."""

    def __init__(self, exported):
        super().__init__()
        self.exported = exported


class _FetchHandle:
    """Fetch placeholder for a loaded inference program output index."""

    __slots__ = ("index", "name")

    def __init__(self, index):
        self.index = index
        self.name = f"fetch_{index}"


def load_inference_model(path_prefix, executor, **kwargs):
    """Returns (program, feed_names, fetch_symbols) runnable via
    Executor.run. Understands both the versioned StableHLO format and the
    legacy cloudpickle one."""
    from .export import ExportedInference, is_stablehlo_model, read_artifacts

    if is_stablehlo_model(path_prefix):
        data_bytes, state, meta = read_artifacts(path_prefix)
        exported = ExportedInference(data_bytes, state, meta)
        prog = InferenceProgram(exported)
        fetches = [_FetchHandle(i) for i in range(meta["fetch_count"])]
        return prog, exported.feed_names, fetches

    import pickle

    from ..framework.io import load as _load

    meta = _load(path_prefix + ".pdiparams")
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = pickle.load(f)
    arrays = [np.asarray(meta["state"][f"__t{i}"])
              for i in range(exported.n_tensors)]
    exprs = exported.bind(arrays)
    prog = Program()
    fetches = [SymbolicTensor(e, None) for e in exprs]
    return prog, meta["feed_names"], fetches


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..framework.core import grad as _grad

    return _grad(targets, inputs, target_gradients, allow_unused=True)


# ---------------------------------------------------------------------------
# places / scope / program-state / serialization surface
# (reference python/paddle/static/__init__.py remaining exports)
# ---------------------------------------------------------------------------

from ..tensor.creation import create_parameter  # noqa: F401,E402
from ..optimizer.optimizer import ExponentialMovingAverage  # noqa: F401,E402
from ..metric import accuracy  # noqa: F401,E402


def cpu_places(device_count=None):
    """List of CPUPlaces (reference framework.py cpu_places); count
    defaults to CPU_NUM=1 like the reference under a TPU runtime."""
    from ..device import CPUPlace

    return [CPUPlace() for _ in range(device_count or 1)]


def cuda_places(device_ids=None):
    """Accelerator places. On this runtime the accelerators are TPU chips:
    returns one place per visible jax device (reference cuda_places
    semantics transposed to the TPU fleet)."""
    import jax

    from ..device import TPUPlace

    devs = jax.devices()
    ids = device_ids if device_ids is not None else range(len(devs))
    return [TPUPlace(int(i)) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


class Scope:
    """name → Tensor registry (reference framework/scope.h:52). The traced
    program captures tensors directly, so the scope is bookkeeping for
    save/load parity, not the execution store."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        from ..framework.core import Tensor

        if name not in self._vars:
            self._vars[name] = Tensor(jnp.zeros((), jnp.float32), name=name)
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def set_var(self, name, t):
        self._vars[name] = t


_global_scope = [Scope()]


def global_scope():
    return _global_scope[-1]


@contextmanager
def scope_guard(scope):
    _global_scope.append(scope)
    try:
        yield
    finally:
        _global_scope.pop()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Persistable global variable (reference layers/tensor.py
    create_global_var); registered in the global scope by name."""
    from ..framework import dtype as dtypes
    from ..framework.core import Tensor

    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        dtypes.convert_dtype(dtype)), name=name)
    t.persistable = persistable
    if name:
        global_scope().set_var(name, t)
    return t


def _print_impl(x, message, summarize):
    jax.debug.print((message + " {}") if message else "{}", x)
    return x + 0 if jnp.issubdtype(x.dtype, jnp.number) else x


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Debug print op (reference controlflow/print_op.cc): prints the
    tensor when the op executes (jax.debug.print inside jit) and passes
    the value through."""
    from ..core.native import shardy_disabled
    from ..framework.core import apply_op

    with shardy_disabled():  # debug-callback lowering predates Shardy
        return apply_op(_print_impl, input, message=message or "",
                        summarize=int(summarize), op_name="Print")


def auc(input, label, curve="ROC", num_thresholds=4095,  # noqa: A002
        topk=1, slide_steps=1, ins_tag_weight=None):
    """Batch AUC by threshold histogram (reference metrics/auc_op.cc —
    same bucketed trapezoid estimate). Returns (auc, batch_auc, states)
    with states = (tp, fp, tn, fn) histograms, like the reference's
    stat outputs."""
    from ..framework.core import apply_op

    def _auc(scores, lab, num_thresholds, curve):
        pos_score = scores[:, 1] if scores.ndim == 2 else scores.reshape(-1)
        lab = lab.reshape(-1)
        bins = jnp.clip((pos_score * num_thresholds).astype(jnp.int32), 0,
                        num_thresholds)
        pos = jnp.zeros(num_thresholds + 1).at[bins].add(lab == 1)
        neg = jnp.zeros(num_thresholds + 1).at[bins].add(lab == 0)
        # cumulative from the highest threshold down
        tp = jnp.cumsum(pos[::-1])[::-1]
        fp = jnp.cumsum(neg[::-1])[::-1]
        tot_pos, tot_neg = tp[0], fp[0]
        tpr = tp / jnp.maximum(tot_pos, 1)
        if curve == "PR":
            precision = tp / jnp.maximum(tp + fp, 1)
            a = jnp.trapezoid(precision[::-1], tpr[::-1])
        else:
            fpr = fp / jnp.maximum(tot_neg, 1)
            a = jnp.trapezoid(tpr[::-1], fpr[::-1])
        return a, a, tp, fp, tot_neg - fp, tot_pos - tp

    if curve not in ("ROC", "PR"):
        raise ValueError("curve must be 'ROC' or 'PR'")
    out = apply_op(_auc, input, label, num_thresholds=int(num_thresholds),
                   curve=curve, op_name="auc")
    return out[0], out[1], tuple(out[2:])


def save(program, model_path, protocol=4, **configs):
    """Persist a program's parameters + buffers to <path>.pdparams AND the
    optimizer state of any minimize()'d optimizers to <path>.pdopt
    (reference static/io.py save writes the same pair; the .pdopt file is
    an empty dict when the program has no train_specs)."""
    from ..framework.io import save as _save

    params = program.all_parameters()
    names = [t.name or f"param_{i}" for i, t in enumerate(params)]
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        raise ValueError(
            "static.save: duplicate parameter names %s — give layers "
            "unique name= arguments" % sorted(dup))
    state = {n: np.asarray(t._data) for n, t in zip(names, params)}
    _save(state, model_path + ".pdparams")
    def _np(v):
        return np.asarray(v._data) if isinstance(v, Tensor) else v

    opt_state = {}
    # ALWAYS prefix with the spec index (previously single-spec programs
    # wrote bare keys): a checkpoint then round-trips into a program with
    # a different optimizer-spec count — load matches by prefix and warns
    # about the specs it cannot fill
    for i, (optimizer, _loss) in enumerate(getattr(program, "train_specs",
                                                   [])):
        sd = optimizer.state_dict()
        opt_state.update({f"opt{i}.{k}": _np(v) for k, v in sd.items()})
    _save(opt_state, model_path + ".pdopt")


def load(program, model_path, executor=None, var_list=None):
    """Restore parameters saved by static.save into the program's
    captured tensors, matched by name; optimizer state is restored from
    the .pdopt companion when present."""
    import os

    from ..framework.io import load as _load

    state = _load(model_path + ".pdparams")
    params = program.all_parameters()
    by_name = {(t.name or f"param_{i}"): t for i, t in enumerate(params)}
    for name, arr in state.items():
        if var_list is not None and name not in {
                getattr(v, "name", v) for v in var_list}:
            continue
        if name in by_name:
            by_name[name].set_value(np.asarray(arr))
    if var_list is None and os.path.exists(model_path + ".pdopt"):
        import re
        import warnings

        opt_state = _load(model_path + ".pdopt")
        specs = getattr(program, "train_specs", [])
        # legacy checkpoints from single-spec programs wrote bare keys
        # (no opt0. prefix) — detect and accept them for spec 0
        has_prefixed = any(re.match(r"opt\d+\.", k) for k in opt_state)
        for i, (optimizer, _loss) in enumerate(specs):
            prefix = f"opt{i}."
            sd = {k[len(prefix):]: v for k, v in opt_state.items()
                  if k.startswith(prefix)}
            if not sd and i == 0 and opt_state and not has_prefixed:
                sd = dict(opt_state)
            if sd:
                optimizer.set_state_dict(sd)
            elif opt_state:
                warnings.warn(
                    f"static.load: no optimizer-state entries under prefix "
                    f"'{prefix}' in {model_path}.pdopt (checkpoint has "
                    f"{len(opt_state)} entries) — optimizer spec {i} keeps "
                    "its current state")


def load_program_state(model_path, var_list=None):
    from ..framework.io import load as _load

    return {k: np.asarray(v)
            for k, v in _load(model_path + ".pdparams").items()}


def set_program_state(program, state_dict):
    params = program.all_parameters()
    by_name = {(t.name or f"param_{i}"): t for i, t in enumerate(params)}
    for name, arr in state_dict.items():
        if name in by_name:
            by_name[name].set_value(np.asarray(arr))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    """Prune to the inference graph (reference static/io.py
    normalize_program). The traced Program already contains only reached
    ops; returns the program annotated with the feed/fetch interface."""
    program._normalized_feeds = [getattr(v, "name", v) for v in feed_vars]
    program._normalized_fetches = list(fetch_vars)
    return program


def _export_cached(feed_vars, fetch_vars, program):
    """One export shared by the serialize pair: tracing + StableHLO
    lowering runs once per (program, feeds, fetches)."""
    from .export import export_fetches

    prog = program or default_main_program()
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    # identity-compared cache with no id() keys: feed/fetch var objects
    # are held strongly (tiny wrappers, prevents address-recycling false
    # hits) and parameter buffers via weakref (set_value rebinds t._data,
    # so updates invalidate the cache, and a dead ref is a miss instead
    # of pinning a stale model copy in device memory)
    import weakref

    bufs = [t._data for t in prog.all_parameters()]
    cached = getattr(prog, "_export_cache", None)
    if cached is not None:
        c_feeds, c_fetches, c_refs, c_result = cached
        c_bufs = [r() for r in c_refs]
        if (len(c_feeds) == len(feed_vars) and len(c_fetches) == len(fetch_vars)
                and all(a is b for a, b in zip(c_feeds, feed_vars))
                and all(a is b for a, b in zip(c_fetches, fetch_vars))
                and len(c_bufs) == len(bufs)
                and all(a is not None and a is b
                        for a, b in zip(c_bufs, bufs))):
            return c_result
    result = export_fetches(feed_vars, fetch_vars,
                            dynamic_dims=prog.feed_dynamic)
    try:
        refs = [weakref.ref(b) for b in bufs]
    except TypeError:
        refs = [(lambda v: (lambda: v))(b) for b in bufs]  # non-weakrefable
    prog._export_cache = (list(feed_vars), list(fetch_vars), refs, result)
    return result


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Program → bytes (reference static/io.py serialize_program): the
    versioned StableHLO export WITHOUT weights."""
    import pickle

    data, state, meta = _export_cached(feed_vars, fetch_vars, program)
    return pickle.dumps({"data": data, "meta": meta})


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    """Weights → bytes, companion of serialize_program."""
    import pickle

    data, state, meta = _export_cached(feed_vars, fetch_vars, program)
    return pickle.dumps([np.asarray(a) for a in state])


def deserialize_program(data):
    """bytes → runnable program shell; weights arrive via
    deserialize_persistables (reference static/io.py pairing)."""
    import pickle

    blob = pickle.loads(data)
    prog = InferenceProgram(None)
    prog._pending = blob
    return prog


def deserialize_persistables(program, data, executor=None):
    """Attach serialized weights to a deserialize_program shell, making it
    runnable by Executor (fetches via program.fetch_handles())."""
    import pickle

    from .export import ExportedInference

    state = pickle.loads(data)
    blob = getattr(program, "_pending", None)
    if blob is None:
        raise ValueError("program was not produced by deserialize_program")
    blob["meta"]["n_state"] = len(state)
    program.exported = ExportedInference(blob["data"], state, blob["meta"])
    program._pending = None
    return program


class ParallelExecutor:
    """reference parallel_executor.py shim: multi-device execution is
    GSPMD batch sharding (CompiledProgram.with_data_parallel); this class
    keeps the constructor/run surface."""

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            self._program, build_strategy).with_data_parallel(
                loss_name=loss_name, exec_strategy=exec_strategy)
        self._exe = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(self._compiled, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


from ..framework.param_attr import WeightNormParamAttr  # noqa: F401,E402
