"""paddle_tpu.static — traced "static graph" mode.

The reference's static world (ProgramDesc + Executor,
framework.py:4393 Program / executor.py:1065 Executor.run) is replaced by
jax tracing: a Program here is a captured python callable + InputSpecs that
compiles to one XLA module. ``Executor.run(feed/fetch)`` keeps the
reference's call signature over that.

This module provides the user-facing shims; the real machinery lives in
paddle_tpu.jit.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional

import jax
import numpy as np

from ..framework.core import Tensor
from ..jit import InputSpec  # noqa: F401

__all__ = [
    "InputSpec", "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "CompiledProgram",
    "name_scope", "device_guard", "py_func", "save_inference_model",
    "load_inference_model", "gradients",
]

_static_mode = [False]


class Variable:
    """Symbolic placeholder in a static Program."""

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype

    def __repr__(self):
        return f"Var({self.name}, shape={self.shape}, dtype={self.dtype})"


class Program:
    """A deferred computation: feeds -> fetches via a traced callable.

    Build with program_guard + paddle_tpu.static.data + a builder function
    registered via ``set_forward`` — or (typical migration path) skip static
    mode entirely and use paddle_tpu.jit.to_static.
    """

    def __init__(self):
        self.feed_vars: Dict[str, Variable] = {}
        self.fetch_builders = []
        self._forward = None
        self.random_seed = None

    def global_block(self):
        return self

    def set_forward(self, fn):
        self._forward = fn
        return fn

    def clone(self, for_test=False):
        import copy

        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextmanager
def program_guard(main_program, startup_program=None):
    pm, ps = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = pm, ps


def data(name, shape, dtype="float32", lod_level=0):
    v = Variable(name, shape, dtype)
    default_main_program().feed_vars[name] = v
    return v


@contextmanager
def name_scope(prefix):
    yield


@contextmanager
def device_guard(device=None):
    """Pipeline-stage placement hint (reference framework.py device_guard).

    In the TPU build, stage placement is declared via PipelineLayer /
    mesh shardings; this context is accepted and recorded as a no-op hint.
    """
    yield


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError("py_func: wrap python code with jax.pure_callback instead")


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy


class Executor:
    """exe.run(feed/fetch) shim over jit (reference executor.py:607)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        program = program or default_main_program()
        if isinstance(program, CompiledProgram):
            program = program.program
        if program._forward is None:
            # startup program: nothing to execute (params init eagerly)
            return []
        feed = feed or {}
        arrays = {k: (v._data if isinstance(v, Tensor) else np.asarray(v)) for k, v in feed.items()}
        fn = self._cache.get(id(program))
        if fn is None:
            fn = jax.jit(program._forward)
            self._cache[id(program)] = fn
        outs = fn(**arrays)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def close(self):
        pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, **kwargs):
    from ..framework.io import save as _save

    _save({"feed": feed_vars, "fetch": fetch_vars}, path_prefix + ".pdmodel.meta")


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError("use paddle_tpu.jit.load")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..framework.core import grad as _grad

    return _grad(targets, inputs, target_gradients, allow_unused=True)
