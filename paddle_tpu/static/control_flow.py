"""Static control-flow ops: cond, while_loop, case, switch_case.

Parity: reference python/paddle/fluid/layers/control_flow.py (`cond`
:2325-ish, `while_loop`, `case`, `switch_case` over ConditionalBlock /
While ops interpreted by the executor).

TPU-native: XLA *is* the interpreter, so these lower directly to
jax.lax.cond / jax.lax.while_loop inside whatever trace is active:

- eager mode: executes immediately (lax primitives run op-by-op);
- to_static / jit tracing: becomes a real HLO While/Conditional;
- symbolic static-graph mode (program_guard capture): ``while_loop``
  records ONE op whose body re-enters the user's cond/body functions on
  traced arrays at replay time; ``cond`` records both branches and selects
  (branches in a paddle static program are pure by construction, so
  evaluating both is semantics-preserving — the same trade XLA itself makes
  when it flattens small conditionals).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _is_symbolic(*vals):
    from .graph import SymbolicTensor

    def walk(v):
        if isinstance(v, SymbolicTensor):
            return True
        if isinstance(v, (tuple, list)):
            return any(walk(x) for x in v)
        return False

    return any(walk(v) for v in vals)


def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _tree_unwrap(tree):
    return jax.tree_util.tree_map(
        _unwrap, tree, is_leaf=lambda x: isinstance(x, Tensor))


def _tree_wrap(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if not isinstance(v, Tensor) else v, tree)


def _check_struct(t_out, f_out, what="cond"):
    ts = jax.tree_util.tree_structure(t_out)
    fs = jax.tree_util.tree_structure(f_out)
    if ts != fs:
        raise ValueError(
            f"{what}: branch outputs must have identical structure, got "
            f"{ts} vs {fs}")


def cond(pred, true_fn: Callable, false_fn: Callable, name=None):
    """Run true_fn() or false_fn() depending on scalar boolean ``pred``.

    Both branch callables take no arguments (they close over outer
    tensors) and must return matching structures of Tensors.
    """
    if _is_symbolic(pred):
        # symbolic build: record both branch subgraphs, then select.
        t_out = true_fn()
        f_out = false_fn()
        _check_struct(t_out, f_out)
        flat_t, treedef = jax.tree_util.tree_flatten(
            t_out, is_leaf=lambda x: isinstance(x, Tensor))
        flat_f = treedef.flatten_up_to(f_out)

        def select(p, *branches):
            n = len(branches) // 2
            p = jnp.reshape(p, ()).astype(bool)
            return tuple(jnp.where(p, a, b)
                         for a, b in zip(branches[:n], branches[n:]))

        out = apply_op(select, pred, *flat_t, *flat_f)
        out = out if isinstance(out, tuple) else (out,)
        return jax.tree_util.tree_unflatten(treedef, out)

    p = jnp.reshape(_unwrap(pred), ()).astype(bool)

    # Trace both branches through lax.cond; closed-over Tensors become
    # implicit operands. Outputs are unwrapped arrays (wrapped back after).
    res_struct = []

    def tf(_):
        out = true_fn()
        res_struct.append(jax.tree_util.tree_structure(
            out, is_leaf=lambda x: isinstance(x, Tensor)))
        return _tree_unwrap(out)

    def ff(_):
        out = false_fn()
        res_struct.append(jax.tree_util.tree_structure(
            out, is_leaf=lambda x: isinstance(x, Tensor)))
        return _tree_unwrap(out)

    out = jax.lax.cond(p, tf, ff, 0)
    if len(res_struct) == 2 and res_struct[0] != res_struct[1]:
        raise ValueError("cond: branch outputs must have identical structure")
    return _tree_wrap(out)


def _closure_symbolics(fn, exclude_ids):
    """Symbolic tensors captured in fn's closure cells: they must become
    explicit operands of the recorded while op, because at replay time
    their build-time avals are swapped for the live traced arrays."""
    from .graph import SymbolicTensor

    found = []
    for f in (fn,):
        cells = getattr(f, "__closure__", None) or ()
        for cell in cells:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if isinstance(v, SymbolicTensor) and id(v) not in exclude_ids:
                exclude_ids.add(id(v))
                found.append(v)
    return found


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """paddle.static.nn.while_loop parity: run ``body_fn(*vars)`` while
    ``cond_fn(*vars)`` holds; returns the final loop vars.

    Lowers to jax.lax.while_loop (an XLA While op). Note XLA's constraint,
    shared with the reference's While op: loop vars must keep shape/dtype
    across iterations.
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")

    n = len(loop_vars)
    seen = {id(v) for v in loop_vars}
    extras = (_closure_symbolics(cond_fn, seen) +
              _closure_symbolics(body_fn, seen))

    def run(*arrays):
        from .graph import suspend_symbolic

        loop_arrays, extra_arrays = arrays[:n], arrays[n:]
        saved = [(t, t._data) for t in extras]
        try:
            with suspend_symbolic():
                for t, a in zip(extras, extra_arrays):
                    t._data = a  # bind live value over the build-time aval

                def c(vs):
                    r = cond_fn(*[Tensor(v) for v in vs])
                    return jnp.reshape(_unwrap(r), ()).astype(bool)

                def b(vs):
                    out = body_fn(*[Tensor(v) for v in vs])
                    if not isinstance(out, (tuple, list)):
                        out = (out,)
                    if len(out) != n:
                        raise ValueError(
                            f"while_loop: body returned {len(out)} vars, "
                            f"expected {n}")
                    return tuple(_unwrap(o).astype(v.dtype).reshape(v.shape)
                                 for o, v in zip(out, vs))

                return jax.lax.while_loop(c, b, tuple(loop_arrays))
        finally:
            for t, d in saved:
                t._data = d

    out = apply_op(run, *loop_vars, *extras)
    if n == 1 and not isinstance(out, (tuple, list)):
        return [out]
    out = list(out) if isinstance(out, (tuple, list)) else [out]
    return out[:n]


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins chain of (pred, fn) pairs (reference
    control_flow.py case): nested cond."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return fn()
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on integer ``branch_index`` (reference switch_case).

    branch_fns: list of callables or list of (index, callable) pairs.
    """
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = sorted((int(i), f) for i, f in branch_fns)
    else:
        pairs = list(enumerate(branch_fns))
    if default is None:
        default = pairs[-1][1]

    chain = default
    for idx, fn in reversed(pairs):
        chain = (lambda chain=chain, idx=idx, fn=fn: cond(
            _eq_scalar(branch_index, idx), fn, chain))
    return chain()


def _eq_scalar(x, i):
    from .. import tensor as T

    if isinstance(x, Tensor):
        return T.equal(x, Tensor(jnp.asarray(i, _unwrap(x).dtype)))
    return x == i
