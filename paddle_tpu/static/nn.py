"""paddle.static.nn parity surface: control flow + static layer helpers.

Reference: python/paddle/static/nn/__init__.py (fc + control_flow ops from
fluid/layers/control_flow.py).
"""
from __future__ import annotations

from .control_flow import case, cond, switch_case, while_loop  # noqa: F401

__all__ = [
    "cond", "while_loop", "case", "switch_case", "fc", "conv2d", "conv3d",
    "conv2d_transpose", "conv3d_transpose", "batch_norm", "layer_norm",
    "group_norm", "instance_norm", "data_norm", "spectral_norm", "embedding",
    "sparse_embedding", "prelu", "bilinear_tensor_product", "row_conv",
    "crf_decoding", "nce", "multi_box_head", "deform_conv2d", "py_func",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]

from ..nn.functional.sequence import (  # noqa: F401,E402
    sequence_concat, sequence_conv, sequence_enumerate, sequence_expand,
    sequence_expand_as, sequence_first_step, sequence_last_step,
    sequence_pad, sequence_pool, sequence_reshape, sequence_reverse,
    sequence_scatter, sequence_slice, sequence_softmax, sequence_unpad,
)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Static fully-connected helper (reference static/nn/common.py fc):
    flattens trailing dims, applies xW+b and optional activation."""
    name = _uname("fc", name)
    import numpy as np

    from .. import tensor as T
    from ..framework.core import Parameter
    from ..nn import functional as F
    from ..nn import initializer as I

    shape = list(x.shape)
    in_features = int(np.prod(shape[num_flatten_dims:]))
    if num_flatten_dims != len(shape) - 1 or len(shape) > 2:
        x = T.reshape(x, shape[:num_flatten_dims] + [in_features])
    w = Parameter(I.XavierNormal()((in_features, size), "float32"),
                  name=name + ".w")
    out = T.matmul(x, w)
    if bias_attr is not False:
        b = Parameter(I.Constant(0.0)((size,), "float32"),
                      name=name + ".b")
        out = out + b
    if activation == "relu":
        out = F.relu(out)
    elif activation == "tanh":
        out = T.tanh(out)
    elif activation == "sigmoid":
        out = F.sigmoid(out)
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation}")
    return out


# ---------------------------------------------------------------------------
# static layer wrappers (reference python/paddle/static/nn/__init__.py):
# each creates its Parameters inline (captured by the traced Program as
# leaves, static/graph.py) and applies the op — the LayerHelper pattern
# without a LayerHelper.
# ---------------------------------------------------------------------------

def _uname(base, name):
    """Auto-unique parameter-name prefix (the reference LayerHelper
    uniquifies every created var; fixed names would collide in
    static.save's name-keyed state dict). Counters live ON the active
    Program so rebuilding the same graph reproduces the same names and
    save/rebuild/load round-trips."""
    if name is not None:
        return name
    from . import default_main_program

    prog = default_main_program()
    counters = getattr(prog, "_uname_counters", None)
    if counters is None:
        counters = prog._uname_counters = {}
    n = counters.get(base, 0)
    counters[base] = n + 1
    return "%s_%d" % (base, n)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    from ..nn import Conv2D

    out = Conv2D(int(input.shape[1]), num_filters, filter_size,
                 stride=stride, padding=padding, dilation=dilation,
                 groups=groups, weight_attr=param_attr, bias_attr=bias_attr,
                 data_format=data_format)(input)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from ..nn import Conv3D

    out = Conv3D(int(input.shape[1]), num_filters, filter_size,
                 stride=stride, padding=padding, dilation=dilation,
                 groups=groups, weight_attr=param_attr, bias_attr=bias_attr,
                 data_format=data_format)(input)
    return _act(out, act)



def _deconv_filter(filter_size, output_size, in_spatial, stride, padding):
    """Reference conv2d_transpose: filter_size derived from output_size
    when omitted (k = out - (in-1)*stride + 2*pad)."""
    if filter_size is not None:
        return filter_size
    if output_size is None:
        raise ValueError(
            "conv transpose needs filter_size or output_size")
    outs = ([int(output_size)] * len(in_spatial)
            if isinstance(output_size, int) else [int(v) for v in output_size])
    st = ([int(stride)] * len(in_spatial) if isinstance(stride, int)
          else [int(v) for v in stride])
    pd = ([int(padding)] * len(in_spatial) if isinstance(padding, int)
          else [int(v) for v in padding])
    return [outs[i] - (int(in_spatial[i]) - 1) * st[i] + 2 * pd[i]
            for i in range(len(in_spatial))]

def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from ..nn import Conv2DTranspose

    filter_size = _deconv_filter(filter_size, output_size, input.shape[2:],
                                 stride, padding)
    out = Conv2DTranspose(int(input.shape[1]), num_filters, filter_size,
                          stride=stride, padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format)(input)
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from ..nn import Conv3DTranspose

    filter_size = _deconv_filter(filter_size, output_size, input.shape[2:],
                                 stride, padding)
    out = Conv3DTranspose(int(input.shape[1]), num_filters, filter_size,
                          stride=stride, padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format)(input)
    return _act(out, act)


def _act(out, act):
    if act is None:
        return out
    from ..nn import functional as F

    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError("unsupported activation %r" % (act,))
    return fn(out)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False):
    from ..nn import BatchNorm

    bn = BatchNorm(int(input.shape[1]), momentum=momentum, epsilon=epsilon,
                   param_attr=param_attr, bias_attr=bias_attr,
                   data_layout=data_layout, use_global_stats=use_global_stats)
    if is_test:
        bn.eval()
    return _act(bn(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import LayerNorm

    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    ln = LayerNorm(shape, epsilon=epsilon,
                   weight_attr=param_attr if scale else False,
                   bias_attr=bias_attr if shift else False)
    return _act(ln(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
               act=None, data_layout="NCHW", name=None):
    from ..nn import GroupNorm

    gn = GroupNorm(groups, int(input.shape[1]), epsilon=epsilon,
                   weight_attr=param_attr, bias_attr=bias_attr)
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
                  name=None):
    from ..nn import InstanceNorm2D

    return InstanceNorm2D(int(input.shape[1]), epsilon=epsilon,
                          weight_attr=param_attr, bias_attr=bias_attr)(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999, enable_scale_and_shift=False):
    """Reference data_norm_op.cc: normalization by accumulated batch
    statistics (batch_size/batch_sum/batch_square_sum), no learned gamma:
    out = (x - sum/size) / sqrt(square_sum/size - mean^2 + eps)."""
    name = _uname("dn", name)
    import jax.numpy as jnp

    from ..framework.core import Parameter, apply_op
    from ..nn import initializer as I

    D = int(input.shape[1])
    # accumulated statistics, NOT gradient-trained (reference data_norm_op
    # updates them by in-place accumulation, not SGD)
    size = Parameter(I.Constant(1e4)((D,), "float32"),
                     name=name + ".size", trainable=False)
    sums = Parameter(I.Constant(0.0)((D,), "float32"),
                     name=name + ".sum", trainable=False)
    sqs = Parameter(I.Constant(1e4)((D,), "float32"),
                    name=name + ".sq", trainable=False)

    def _dn(x, size, sums, sqs, epsilon):
        mean = sums / size
        var = sqs / size - mean * mean
        return (x - mean) / jnp.sqrt(var + epsilon)

    return _act(apply_op(_dn, input, size, sums, sqs,
                         epsilon=float(epsilon), op_name="data_norm"), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalization of a weight tensor
    (reference spectral_norm_op.cc), returning weight / sigma."""
    import jax.numpy as jnp

    from ..framework.core import apply_op

    def _sn(w, dim, power_iters, eps):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / jnp.sqrt(wm.shape[0])
        v = jnp.ones((wm.shape[1],), w.dtype) / jnp.sqrt(wm.shape[1])
        for _ in range(max(power_iters, 1)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma

    return apply_op(_sn, weight, dim=int(dim), power_iters=int(power_iters),
                    eps=float(eps), op_name="spectral_norm")


def embedding(input, size, is_sparse=False, is_distributed=False,  # noqa: A002
              padding_idx=None, param_attr=None, dtype="float32"):
    from ..nn import Embedding

    emb = Embedding(int(size[0]), int(size[1]), padding_idx=padding_idx,
                    sparse=is_sparse, weight_attr=param_attr)
    return emb(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, param_attr=None, dtype="float32"):
    """Reference sparse_embedding: PS-backed huge embedding table. Per the
    parameter-server decision (README), the table is dense here; ``entry``
    admission configs are accepted and ignored."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def prelu(x, mode="all", param_attr=None, name=None):
    from ..framework.core import Parameter
    from ..nn import functional as F
    from ..nn import initializer as I

    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[1]),)
    elif mode == "element":
        shape = tuple(int(s) for s in x.shape[1:])
    else:
        raise ValueError("mode must be all/channel/element")
    alpha = Parameter(I.Constant(0.25)(shape, "float32"),
                      name=_uname("prelu", name) + ".alpha")
    return F.prelu(x, alpha)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x W_k y^T + b (reference bilinear_tensor_product_op.cc)."""
    name = _uname("btp", name)
    import jax.numpy as jnp

    from ..framework.core import Parameter, apply_op
    from ..nn import initializer as I

    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = Parameter(I.XavierNormal()((size, dx, dy), "float32"),
                  name=name + ".w")
    b = Parameter(I.Constant(0.0)((size,), "float32"),
                  name=name + ".b")

    def _btp(x, y, w, b):
        return jnp.einsum("bd,kde,be->bk", x, w, y) + b

    return _act(apply_op(_btp, x, y, w, b, op_name="bilinear_tensor_product"),
                act)


def row_conv(input, future_context_size, param_attr=None, act=None):  # noqa: A002
    """Lookahead row convolution (reference row_conv_op.cc):
    out[t] = sum_{i=0..ctx} w[i] * x[t+i], per feature."""
    import jax.numpy as jnp

    from ..framework.core import Parameter, apply_op
    from ..nn import initializer as I

    D = int(input.shape[-1])
    ctx = int(future_context_size) + 1
    w = Parameter(I.XavierNormal()((ctx, D), "float32"), name=_uname("row_conv", None) + ".w")

    def _rc(x, w):
        T = x.shape[1]
        out = jnp.zeros_like(x)
        for i in range(w.shape[0]):
            shifted = jnp.roll(x, -i, axis=1)
            ok = (jnp.arange(T) + i < T)[None, :, None]
            out = out + jnp.where(ok, shifted, 0.0) * w[i]
        return out

    return _act(apply_op(_rc, input, w, op_name="row_conv"), act)


def crf_decoding(input, param_attr, label=None, length=None):  # noqa: A002
    """Viterbi decode with learned CRF transitions (reference
    crf_decoding_op.h). ``param_attr`` here IS the transition tensor
    ([num_tags + 2, num_tags]: rows 0/1 are start/stop, like
    linear_chain_crf_op) — the reference resolved it by parameter name
    through the Scope, which the traced program replaces with direct
    capture."""
    import jax.numpy as jnp

    from ..framework.core import Tensor
    from ..text import viterbi_decode

    trans = param_attr
    ta = trans._data if isinstance(trans, Tensor) else jnp.asarray(trans)
    # linear_chain_crf layout [num_tags+2, num_tags]: row 0 = start scores,
    # row 1 = stop scores, rows 2.. = pairwise. Fold start/stop into the
    # emissions, decode with the pairwise matrix.
    emis = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    B, T_len, _ = emis.shape
    if length is not None:
        lens = (length._data if isinstance(length, Tensor)
                else jnp.asarray(length)).reshape(-1)
    else:
        lens = jnp.full((B,), T_len, jnp.int32)
    emis = emis.at[:, 0].add(ta[0])
    last = jnp.maximum(lens - 1, 0).astype(jnp.int32)
    emis = emis.at[jnp.arange(B), last].add(ta[1])
    scores, path = viterbi_decode(Tensor(emis), Tensor(ta[2:]), Tensor(lens),
                                  include_bos_eos_tag=False)
    if label is not None:
        from ..framework.core import apply_op

        return apply_op(lambda p, l: (p == l.reshape(p.shape)).astype("int64"),
                        path, label, op_name="crf_decoding_check")
    return path


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce_op.h): binary
    logistic on the true class vs num_neg_samples uniform negatives."""
    name = _uname("nce", name)
    import jax
    import jax.numpy as jnp

    from ..framework.core import Parameter, apply_op
    from ..framework.random import next_key
    from ..nn import initializer as I

    if sampler != "uniform" or custom_dist is not None:
        raise NotImplementedError(
            "nce: only the uniform sampler is implemented")
    D = int(input.shape[-1])
    w = Parameter(I.XavierNormal()((num_total_classes, D), "float32"),
                  name=name + ".w")
    b = Parameter(I.Constant(0.0)((num_total_classes,), "float32"),
                  name=name + ".b")
    # Eager mode: negatives refresh per call — seed=0 draws from the
    # advancing global PRNG; an explicit seed gets a deterministic but
    # still advancing stream (fold_in of a call counter), matching the
    # reference sampler. Static mode captures the build-time key, the same
    # frozen-randomness semantics as every random op in a traced Program
    # (see nn/functional/common.py dropout).
    if seed:
        # per-Program call index (like _uname): rebuilding the same graph
        # reproduces the same seeded negatives, while repeated eager calls
        # still advance
        from . import default_main_program

        prog = default_main_program()
        idx = getattr(prog, "_nce_counter", 0) + 1
        prog._nce_counter = idx
        key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
    else:
        key = next_key()
    from ..framework.core import Tensor as _T

    def _nce(x, lab, w, b, key, num_neg_samples, num_total_classes):
        neg = jax.random.randint(key, (num_neg_samples,), 0,
                                 num_total_classes)
        lab = lab.reshape(-1)
        pos_logit = jnp.sum(x * w[lab], -1) + b[lab]
        neg_logit = x @ w[neg].T + b[neg]              # [B, S]
        # P(noise) = 1/num_total_classes under the uniform sampler
        log_noise = jnp.log(jnp.asarray(
            num_neg_samples / num_total_classes, x.dtype))
        pos = jax.nn.softplus(-(pos_logit - log_noise))
        negl = jax.nn.softplus(neg_logit - log_noise)
        return (pos + jnp.sum(negl, -1))[:, None]

    return apply_op(_nce, input, label, w, b, _T(key),
                    num_neg_samples=int(num_neg_samples),
                    num_total_classes=int(num_total_classes), op_name="nce")


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference detection/multi_box_head in
    fluid/layers/detection.py): per feature map, conv loc/conf predictions
    + prior boxes; outputs concatenated (mbox_locs [N,M,4], mbox_confs
    [N,M,C], prior_boxes [M,4], variances [M,4])."""
    import numpy as np

    from .. import tensor as T
    from ..vision.ops import prior_box

    if min_sizes is None:
        # reference ratio schedule (detection.py multi_box_head)
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        # reference ratio schedule needs >=3 maps; with fewer, span the
        # [min_ratio, max_ratio] range directly
        step = (int(np.floor((max_ratio - min_ratio) / (num_layer - 2)))
                if num_layer > 2 else (max_ratio - min_ratio))
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = ([base_size * 0.10] + min_sizes)[:num_layer]
        max_sizes = ([base_size * 0.20] + max_sizes)[:num_layer]

    locs, confs, boxes, vars_ = [], [], [], []
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        mins = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        maxs = max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) \
            else [max_sizes[i]]
        box, var = prior_box(feat, image, mins, maxs, ar, list(variance),
                             flip=flip, clip=clip,
                             steps=[steps[i], steps[i]] if steps else [0.0, 0.0],
                             offset=offset,
                             min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        num_priors = int(box.shape[0] * box.shape[1] * box.shape[2]) // (
            int(feat.shape[2]) * int(feat.shape[3]))
        loc = conv2d(feat, num_priors * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad)
        n = int(feat.shape[0])
        locs.append(T.reshape(T.transpose(loc, [0, 2, 3, 1]), [n, -1, 4]))
        confs.append(T.reshape(T.transpose(conf, [0, 2, 3, 1]),
                               [n, -1, num_classes]))
        boxes.append(T.reshape(box, [-1, 4]))
        vars_.append(T.reshape(var, [-1, 4]))
    return (T.concat(locs, 1), T.concat(confs, 1), T.concat(boxes, 0),
            T.concat(vars_, 0))


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None, name=None):
    name = _uname("dcn", name)
    from ..framework.core import Parameter
    from ..nn import initializer as I
    from ..vision.ops import deform_conv2d as _dc

    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = int(x.shape[1])
    w = Parameter(I.XavierNormal()((num_filters, cin // groups, k[0], k[1]),
                                   "float32"), name=name + ".w")
    b = None
    if bias_attr is not False:
        b = Parameter(I.Constant(0.0)((num_filters,), "float32"),
                      name=name + ".b")
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference py_func_op.cc) via jax.pure_callback: runs
    ``func`` on host values even under jit. ``out`` is the template
    Tensor(s) declaring result shape/dtype. backward_func is not supported
    — wrap differentiable logic in ops instead (documented refusal; the
    reference runs backward_func only in static autodiff)."""
    import jax
    import numpy as np

    from ..framework.core import Tensor, apply_op

    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func is not supported; compose differentiable "
            "ops or use a custom op (utils/custom_op.py)")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(str(o.dtype)))
             for o in outs]
    multi = isinstance(out, (list, tuple))

    def _impl(*arrays):
        res = jax.pure_callback(
            lambda *hs: func(*hs) if multi else (func(*hs),), tuple(specs),
            *arrays)
        return tuple(res) if multi else res[0]

    from ..core.native import shardy_disabled

    with shardy_disabled():  # callback lowering predates Shardy (jax 0.4.x)
        return apply_op(_impl, *xs, op_name="py_func")
