"""paddle.static.nn parity surface: control flow + static layer helpers.

Reference: python/paddle/static/nn/__init__.py (fc + control_flow ops from
fluid/layers/control_flow.py).
"""
from __future__ import annotations

from .control_flow import case, cond, switch_case, while_loop  # noqa: F401

__all__ = ["cond", "while_loop", "case", "switch_case", "fc"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Static fully-connected helper (reference static/nn/common.py fc):
    flattens trailing dims, applies xW+b and optional activation."""
    import numpy as np

    from .. import tensor as T
    from ..framework.core import Parameter
    from ..nn import functional as F
    from ..nn import initializer as I

    shape = list(x.shape)
    in_features = int(np.prod(shape[num_flatten_dims:]))
    if num_flatten_dims != len(shape) - 1 or len(shape) > 2:
        x = T.reshape(x, shape[:num_flatten_dims] + [in_features])
    w = Parameter(I.XavierNormal()((in_features, size), "float32"),
                  name=(name or "fc") + ".w")
    out = T.matmul(x, w)
    if bias_attr is not False:
        b = Parameter(I.Constant(0.0)((size,), "float32"),
                      name=(name or "fc") + ".b")
        out = out + b
    if activation == "relu":
        out = F.relu(out)
    elif activation == "tanh":
        out = T.tanh(out)
    elif activation == "sigmoid":
        out = F.sigmoid(out)
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation}")
    return out
