"""Symbolic graph capture for static mode.

The reference's static world records ops into a ProgramDesc as user code
calls layer functions on symbolic Variables (reference framework.py:3222
Block.append_op via layer helpers). Here the SAME op funnel the eager mode
uses (framework/core.py apply_op) records into the current Program when any
input is symbolic: an op node keeps the pure jax function + its symbolic/
literal args, and execution later REPLAYS the recorded DAG inside one
jax.jit — so "building a program" and "tracing for XLA" are the same
mechanism, and every eager op is automatically available in static mode
(the reference needed a separate wrapper per op in fluid/layers).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, set_symbolic_dispatch

__all__ = ["SymbolicTensor", "OpRecord", "SymExpr", "evaluate_exprs",
           "collect_leaves"]


class OpRecord:
    """One recorded op: pure fn + (symbolic|literal) args + attrs.
    ``program`` is set by the Program that recorded it, so later calls
    (e.g. optimizer.minimize outside the program_guard) can find the
    owning program."""

    __slots__ = ("fn", "args", "attrs", "name", "n_outputs", "program")

    def __init__(self, fn, args, attrs, name):
        self.fn = fn
        self.args = args          # SymExpr | Tensor | literal per position
        self.attrs = attrs
        self.name = name
        self.n_outputs = 1
        self.program = None


class SymExpr:
    """A value in the symbolic graph.

    kind: "feed" (runtime placeholder), "tensor" (captured eager Tensor —
    typically a Parameter; evaluated to its CURRENT value at run time),
    "op" (output ``index`` of an OpRecord).
    """

    __slots__ = ("kind", "name", "tensor", "op", "index", "aval")

    def __init__(self, kind, name=None, tensor=None, op=None, index=0,
                 aval=None):
        self.kind = kind
        self.name = name
        self.tensor = tensor
        self.op = op
        self.index = index
        self.aval = aval


class SymbolicTensor(Tensor):
    """Tensor whose ``_data`` is an abstract aval; carries the SymExpr."""

    __slots__ = ("_expr",)

    def __init__(self, expr: SymExpr, aval: jax.ShapeDtypeStruct):
        # bypass Tensor.__init__'s jnp.asarray
        self._data = aval
        self.stop_gradient = True
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = expr.name
        self.persistable = False
        self.sharding = None
        self._expr = expr

    def numpy(self):
        raise RuntimeError(
            "SymbolicTensor has no value at build time — fetch it through "
            "Executor.run(fetch_list=[...])")

    def __repr__(self):
        return (f"SymbolicTensor(name={self.name}, shape={list(self._data.shape)}, "
                f"dtype={self._data.dtype})")


def _fake_aval(x):
    if isinstance(x, SymbolicTensor):
        return x._data
    if isinstance(x, Tensor):
        return jax.ShapeDtypeStruct(tuple(x._data.shape), x._data.dtype)
    return x


_suspended = [0]


class suspend_symbolic:
    """Context: execute ops directly even if SymbolicTensor instances are
    reachable (used by control-flow op bodies at replay time, where
    build-time symbolic tensors have live arrays bound into ``_data``)."""

    def __enter__(self):
        _suspended[0] += 1

    def __exit__(self, *exc):
        _suspended[0] -= 1
        return False


def _symbolic_dispatch(fn, args, attrs, op_name):
    """Installed into framework.core.apply_op: record instead of execute
    when any arg is symbolic."""
    if _suspended[0]:
        return NotImplemented
    if not any(isinstance(a, SymbolicTensor) for a in args):
        return NotImplemented

    rec_args = []
    for a in args:
        if isinstance(a, SymbolicTensor):
            rec_args.append(a._expr)
        elif isinstance(a, Tensor):
            rec_args.append(SymExpr("tensor", tensor=a))
        else:
            rec_args.append(a)
    rec = OpRecord(fn, rec_args, attrs, op_name or getattr(fn, "__name__", "op"))

    # shape/dtype inference via eval_shape on the abstract inputs
    avals = [_fake_aval(a) for a in args]

    def shaped(*xs):
        return fn(*xs, **attrs)

    out_aval = jax.eval_shape(shaped, *avals)
    multi = isinstance(out_aval, (tuple, list))
    outs = tuple(out_aval) if multi else (out_aval,)
    rec.n_outputs = len(outs)
    result = [SymbolicTensor(SymExpr("op", op=rec, index=i, aval=o), o)
              for i, o in enumerate(outs)]
    # register into the active program, if one is listening
    from . import _on_op_recorded

    _on_op_recorded(rec)
    return tuple(result) if multi else result[0]


set_symbolic_dispatch(_symbolic_dispatch)


# -- evaluation -------------------------------------------------------------

def collect_leaves(exprs: List[SymExpr]):
    """Return (feed_names, tensor_leaves) reachable from exprs; tensor
    leaves are the captured eager Tensors (Parameters etc.), deduped by id,
    in deterministic discovery order."""
    feeds: List[str] = []
    tensors: List[Tensor] = []
    seen_ops = set()
    seen_feed = set()
    seen_t = set()

    def walk(e):
        if not isinstance(e, SymExpr):
            return
        if e.kind == "feed":
            if e.name not in seen_feed:
                seen_feed.add(e.name)
                feeds.append(e.name)
        elif e.kind == "tensor":
            if id(e.tensor) not in seen_t:
                seen_t.add(id(e.tensor))
                tensors.append(e.tensor)
        elif e.kind == "op":
            if id(e.op) in seen_ops:
                return
            seen_ops.add(id(e.op))
            for a in e.op.args:
                walk(a)

    for e in exprs:
        walk(e)
    return feeds, tensors


def grad_of_loss(loss_expr: SymExpr, params, feed_env: Dict[str, Any],
                 tensor_env: Dict[int, Any]):
    """dloss/dparams by replaying the loss subgraph under jax.grad with the
    params as traced inputs (shared by append_backward's grad op and the
    Executor train path; XLA CSEs the duplicated forward inside one jit)."""
    base = [tensor_env.get(id(p), p._data) for p in params]

    def loss_fn(param_arrays):
        te = dict(tensor_env)
        te.update({id(p): a for p, a in zip(params, param_arrays)})
        (lv,) = evaluate_exprs([loss_expr], feed_env, te)
        return lv.astype(jnp.float32) if lv.dtype != jnp.float32 else lv

    return tuple(jax.grad(loss_fn)(base))


def evaluate_exprs(exprs: List[SymExpr], feed_env: Dict[str, Any],
                   tensor_env: Optional[Dict[int, Any]] = None):
    """Replay the DAG; returns the list of values for ``exprs``.

    ``tensor_env`` maps id(tensor) → array, letting the caller substitute
    traced values for captured Parameters (how grads are taken)."""
    tensor_env = tensor_env or {}
    memo: Dict[int, Any] = {}

    def ev(e):
        if not isinstance(e, SymExpr):
            return e
        if e.kind == "feed":
            try:
                return feed_env[e.name]
            except KeyError:
                raise KeyError(f"missing feed for placeholder '{e.name}'")
        if e.kind == "tensor":
            if id(e.tensor) in tensor_env:
                return tensor_env[id(e.tensor)]
            return e.tensor._data
        # op
        if id(e.op) not in memo:
            if hasattr(e.op.fn, "evaluate_with_env"):
                # env-aware ops (static grad op): need the full replay
                # context, not just materialized args
                out = e.op.fn.evaluate_with_env(feed_env, tensor_env)
            else:
                argvals = [ev(a) for a in e.op.args]
                out = e.op.fn(*argvals, **e.op.attrs)
            memo[id(e.op)] = tuple(out) if isinstance(out, (tuple, list)) else (out,)
        return memo[id(e.op)][e.index]

    return [ev(e) for e in exprs]
