"""Versioned inference-program export over jax.export (StableHLO).

Replaces the round-1/2 cloudpickle replay with the TPU-native analog of the
reference's versioned ProgramDesc proto
(/root/reference/paddle/fluid/framework/framework.proto:234 — ProgramDesc
with an op-version map giving forward compatibility): jax.export serializes
the traced program as StableHLO with its own calling-convention version and
platform tags, loadable WITHOUT any of the Python that built it.

Files written for prefix P (names follow reference fluid/io.py
save_inference_model):
  P.pdmodel       magic header + format version + serialized StableHLO
  P.pdiparams     npz of captured state (parameters/buffers)
  P.pdmeta.json   feed names/shapes/dtypes, fetch count, format_version

Dynamic feed dims (static.data shape -1) export as jax.export symbolic
dimensions, so one artifact serves any batch size; when an op in the graph
cannot trace symbolically the export falls back to the concrete build
shapes and records that in the meta.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1
MAGIC = b"PTPU_STABLEHLO\x00"

__all__ = ["FORMAT_VERSION", "MAGIC", "export_fetches", "write_artifacts",
           "read_artifacts", "ExportedInference"]


def _feed_avals(feed_vars, dynamic_dims: Dict[str, List[int]], scope):
    from jax import export as jexport

    avals = []
    for k, t in enumerate(feed_vars):
        shape = tuple(t._data.shape)
        dyn = set(dynamic_dims.get(t.name, ()))
        if dyn and scope is not None:
            dims = []
            for i, s in enumerate(shape):
                if i in dyn:
                    dims.append(jexport.symbolic_shape(f"d{k}_{i}",
                                                      scope=scope)[0])
                else:
                    dims.append(int(s))
            avals.append(jax.ShapeDtypeStruct(tuple(dims), t._data.dtype))
        else:
            avals.append(jax.ShapeDtypeStruct(shape, t._data.dtype))
    return avals


def export_fetches(feed_vars, fetch_vars, dynamic_dims=None,
                   platforms=("cpu", "tpu")):
    """Trace the fetch DAG into a serialized jax.export artifact.

    Returns (serialized_bytes, state_arrays, meta_dict).
    """
    from jax import export as jexport

    from .graph import collect_leaves, evaluate_exprs

    dynamic_dims = dynamic_dims or {}
    exprs = [t._expr for t in fetch_vars]
    _, tensors = collect_leaves(exprs)
    feed_names = [t.name for t in feed_vars]
    state = [np.asarray(t._data) for t in tensors]

    def pure(state_list, feed_list):
        feed_env = dict(zip(feed_names, feed_list))
        tensor_env = {id(t): a for t, a in zip(tensors, state_list)}
        return tuple(evaluate_exprs(exprs, feed_env, tensor_env))

    state_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state]

    symbolic = bool(dynamic_dims)
    err = None
    for use_symbolic in ([True, False] if symbolic else [False]):
        try:
            scope = jexport.SymbolicScope() if use_symbolic else None
            avals = _feed_avals(feed_vars, dynamic_dims if use_symbolic else {},
                                scope)
            exported = jexport.export(jax.jit(pure),
                                      platforms=list(platforms))(
                state_avals, avals)
            data = bytes(exported.serialize())
            meta = {
                "format_version": FORMAT_VERSION,
                "feed_names": feed_names,
                "feed_dtypes": [str(np.dtype(t._data.dtype))
                                for t in feed_vars],
                "feed_shapes": [
                    [-1 if i in set(dynamic_dims.get(t.name, ())) else int(s)
                     for i, s in enumerate(t._data.shape)]
                    for t in feed_vars],
                "fetch_count": len(fetch_vars),
                "n_state": len(state),
                "symbolic_dims": bool(use_symbolic and dynamic_dims),
                "platforms": list(platforms),
            }
            return data, state, meta
        except Exception as e:  # symbolic trace failed: concrete fallback
            err = e
            continue
    raise RuntimeError(f"export failed: {err}")


def export_callable(fn, state, example_feeds, feed_names=None,
                    dynamic_batch=True, platforms=("cpu", "tpu")):
    """Export an arbitrary jittable ``fn(state_list, *feeds) -> outputs``.

    Used by paddle_tpu.jit.save for eager Layers (functional_call closure)
    and by model code that bypasses the symbolic program. ``state`` is a
    list of arrays baked into the artifact; feeds are runtime inputs. With
    dynamic_batch=True the leading dim of every feed is exported
    symbolically (one artifact serves any batch size), falling back to
    concrete shapes if symbolic tracing fails.
    """
    from jax import export as jexport

    state = [np.asarray(a) for a in state]
    example_feeds = [np.asarray(a) for a in example_feeds]
    feed_names = feed_names or [f"x{i}" for i in range(len(example_feeds))]
    state_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state]

    def pure(state_list, feed_list):
        out = fn(state_list, *feed_list)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(out)

    err = None
    for use_symbolic in ([True, False] if dynamic_batch else [False]):
        try:
            if use_symbolic:
                scope = jexport.SymbolicScope()
                avals = [
                    jax.ShapeDtypeStruct(
                        (jexport.symbolic_shape(f"b{k}", scope=scope)[0],)
                        + tuple(a.shape[1:]), a.dtype)
                    if a.ndim else jax.ShapeDtypeStruct((), a.dtype)
                    for k, a in enumerate(example_feeds)]
            else:
                avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                         for a in example_feeds]
            exported = jexport.export(jax.jit(pure),
                                      platforms=list(platforms))(
                state_avals, avals)
            n_out = len(exported.out_avals)
            meta = {
                "format_version": FORMAT_VERSION,
                "feed_names": feed_names,
                "feed_dtypes": [str(a.dtype) for a in example_feeds],
                "feed_shapes": [
                    ([-1] + list(a.shape[1:])) if (use_symbolic and a.ndim)
                    else list(a.shape)
                    for a in example_feeds],
                "fetch_count": n_out,
                "n_state": len(state),
                "symbolic_dims": use_symbolic,
                "platforms": list(platforms),
            }
            return bytes(exported.serialize()), state, meta
        except Exception as e:
            err = e
            continue
    raise RuntimeError(f"export failed: {err}")


def write_artifacts(path_prefix, data: bytes, state, meta):
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(MAGIC)
        f.write(FORMAT_VERSION.to_bytes(4, "little"))
        f.write(data)
    np.savez(path_prefix + ".pdiparams",
             **{f"t{i}": a for i, a in enumerate(state)})
    # np.savez appends .npz; rename to the paddle-style filename
    if os.path.exists(path_prefix + ".pdiparams.npz"):
        os.replace(path_prefix + ".pdiparams.npz", path_prefix + ".pdiparams")
    with open(path_prefix + ".pdmeta.json", "w") as f:
        json.dump(meta, f, indent=1)


def is_stablehlo_model(path_prefix) -> bool:
    p = path_prefix + ".pdmodel"
    if not os.path.exists(p):
        return False
    with open(p, "rb") as f:
        return f.read(len(MAGIC)) == MAGIC


def read_artifacts(path_prefix):
    with open(path_prefix + ".pdmodel", "rb") as f:
        blob = f.read()
    if not blob.startswith(MAGIC):
        raise ValueError(f"{path_prefix}.pdmodel is not a StableHLO export")
    off = len(MAGIC)
    version = int.from_bytes(blob[off:off + 4], "little")
    if version > FORMAT_VERSION:
        raise ValueError(
            f"model format version {version} is newer than this runtime's "
            f"{FORMAT_VERSION}")
    data = blob[off + 4:]
    with open(path_prefix + ".pdmeta.json") as f:
        meta = json.load(f)
    npz = np.load(path_prefix + ".pdiparams")
    state = [npz[f"t{i}"] for i in range(meta["n_state"])]
    return data, state, meta


class ExportedInference:
    """Deserialized artifact: ``run(feeds)`` executes the StableHLO program
    with the captured state. Used by load_inference_model and the
    Predictor; needs NO model-building Python."""

    def __init__(self, data: bytes, state, meta):
        from jax import export as jexport

        self.meta = meta
        self._exported = jexport.deserialize(bytearray(data))
        self._state = [jnp.asarray(a) for a in state]  # device-resident
        self._call = jax.jit(self._exported.call)

    @property
    def feed_names(self):
        return list(self.meta["feed_names"])

    def run(self, feed: Dict[str, Any]):
        feeds = []
        for name, want_dt, want_sh in zip(self.meta["feed_names"],
                                          self.meta["feed_dtypes"],
                                          self.meta["feed_shapes"]):
            if name not in feed:
                raise KeyError(f"missing feed '{name}'")
            a = jnp.asarray(feed[name])
            got = list(a.shape)
            if len(got) != len(want_sh) or any(
                    w != -1 and g != w for g, w in zip(got, want_sh)):
                raise ValueError(
                    f"feed '{name}': shape {got} does not match exported "
                    f"spec {want_sh}"
                    + ("" if self.meta.get("symbolic_dims")
                       else " (model was exported with concrete shapes)"))
            feeds.append(a.astype(want_dt))
        return list(self._call(self._state, feeds))
