"""paddle_tpu.io — Dataset / DataLoader.

Parity: reference python/paddle/io (fluid/reader.py:146 DataLoader,
fluid/dataloader/*). The reference's C++ half (BufferedReader async H2D,
LoDTensorBlockingQueue) maps to a background-thread prefetcher that
overlaps host batch assembly with device compute; on TPU jax.device_put
is async so a small prefetch depth suffices.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core.native import use_shared_memory as _shm_flag
from ..framework import random as grandom
from ..framework.core import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
    "DevicePrefetcher", "prefetch_to_device",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    # holds device buffers — DataLoader must not hand these to forked
    # workers (fork-after-XLA-init deadlock); the threaded path is used
    _holds_device_arrays = True

    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        assert len(lengths) == 1, "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, size=self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        assert (dataset is None) != (sampler is None), "provide dataset xor sampler"
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference python/paddle/io/..
    DistributedBatchSampler / fluid/dataloader/batch_sampler.py:161).
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
            self.epoch += 1
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id=0, num_workers=0, dataset=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: List[Optional[_WorkerInfo]] = [None]


def get_worker_info():
    return _worker_info[0]


def _to_numpy_tree(x):
    """Tensors → numpy for cross-process pickling (workers must not ship
    device buffers)."""
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    if isinstance(x, (list, tuple)):
        return type(x)(_to_numpy_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _to_numpy_tree(v) for k, v in x.items()}
    return x


def _from_numpy_tree(x):
    if isinstance(x, np.ndarray):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_from_numpy_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _from_numpy_tree(v) for k, v in x.items()}
    return x


def _dataset_holds_device_arrays(ds, depth=0) -> bool:
    """Recursively detect device buffers behind dataset wrappers
    (Subset/ComposeDataset/ChainDataset or anything exposing .dataset(s))."""
    if depth > 8:
        return True  # unknown deep nesting — be safe
    if getattr(ds, "_holds_device_arrays", False):
        return True
    for attr in ("dataset", "datasets"):
        inner = getattr(ds, attr, None)
        if inner is None:
            continue
        if isinstance(inner, (list, tuple)):
            if any(_dataset_holds_device_arrays(d, depth + 1) for d in inner):
                return True
        elif _dataset_holds_device_arrays(inner, depth + 1):
            return True
    return False


def _mp_worker_loop(wid, nw, dataset, worker_init_fn, in_q, out_q,
                    ring_cfg=None, stop_event=None):
    """DataLoader child-process loop (module-level so spawn can pickle it).

    numpy-only in the child: never touches XLA. With ``ring_cfg`` the
    worker ships batches through the shared-memory ring (descriptors only
    on the queue — see shm_ring.py); a batch the ring can't take (non-
    numpy leaves, bigger than a slot) falls back to the pickled payload
    for that batch only."""
    import pickle

    from .shm_ring import WorkerRing

    _worker_info[0] = _WorkerInfo(wid, nw, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    ring = None
    if ring_cfg is not None:
        try:
            ring = WorkerRing(ring_cfg)
        except Exception:  # platform error → pipe transport
            ring = None
    try:
        while True:
            job = in_q.get()
            if job is None:
                break
            seq, idxs = job
            try:
                samples = [_to_numpy_tree(dataset[i]) for i in idxs]
                batch = _numpy_collate_fn(samples)
                desc = None
                if ring is not None:
                    desc = ring.put_batch(batch, stop_event)
                if desc is not None:
                    out_q.put((seq, ("shm", desc), None))
                else:
                    if stop_event is not None and stop_event.is_set():
                        break
                    payload = pickle.dumps(
                        batch, protocol=pickle.HIGHEST_PROTOCOL)
                    out_q.put((seq, payload, None))
            except Exception as e:  # noqa: BLE001
                out_q.put((seq, None, repr(e)))
    finally:
        if ring is not None:
            ring.close()


def _numpy_collate_fn(batch):
    """default_collate_fn that stays in numpy — used inside forked workers,
    which must never touch XLA."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(_numpy_collate_fn(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: _numpy_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp

        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    """Loader with real multiprocess workers (reference
    fluid/dataloader/dataloader_iter.py + worker.py) behind ``num_workers``.

    num_workers>0 forks/spawns a worker pool: children index the dataset
    and collate IN NUMPY (never touching XLA), ship batches through the
    SHARED-MEMORY ring (shm_ring.py — descriptors only on the queue, the
    reference's flags.use_shared_memory transport; pickled pipe payloads
    remain the automatic per-batch/per-epoch fallback and the
    `use_shared_memory=False` / FLAGS_use_shared_memory=0 path), and a
    reader thread pushes frames through the NATIVE blocking queue
    (core/csrc/ptpu_core.cc, the LoDTensorBlockingQueue analog) for
    bounded prefetch — so a PIL/augmentation-heavy pipeline escapes the
    GIL and scales with workers (tests/test_native_core.py pins >=2x at 4
    workers; tests/test_io_fastpath.py pins shm >= 1.5x pipe). Falls back
    to a prefetch THREAD when multiprocessing can't preserve semantics:
    custom collate_fn (sees in-process Tensors), IterableDataset
    sharding, device arrays reachable from the dataset (fork-after-XLA
    hazard), or an unpicklable dataset under spawn.
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_size = batch_size
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = None
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no length")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self._iterable_ds:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._gen_batches()
            return
        # fork workers only when safe AND semantics-preserving: the default
        # collate (custom collate_fns see Tensors in-process — the threaded
        # path keeps that contract) and no device buffers reachable from
        # the dataset (fork-after-XLA-init hazard). Transport (shared
        # memory vs pipe) is chosen inside _iter_multiprocess.
        if not self._iterable_ds \
                and self.batch_sampler is not None \
                and self.collate_fn is default_collate_fn \
                and not _dataset_holds_device_arrays(self.dataset) \
                and self._mp_payload_picklable():
            yield from self._iter_multiprocess()
            return
        yield from self._iter_threaded()

    def _mp_payload_picklable(self) -> bool:
        """spawn/forkserver workers receive the dataset by pickle; an
        unpicklable dataset (or init fn) falls back to the thread path.
        The probe is O(dataset size), so its result is cached per
        (dataset, init_fn) identity — one probe, not one per epoch."""
        if self._mp_context().get_start_method() == "fork":
            return True
        key = (id(self.dataset), id(getattr(self, "worker_init_fn", None)))
        cached = getattr(self, "_pickle_probe", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        import pickle

        try:
            pickle.dumps((self.dataset, getattr(self, "worker_init_fn", None)))
            ok = True
        except Exception:
            ok = False
        self._pickle_probe = (key, ok)
        return ok

    def _iter_threaded(self):
        # buffered prefetch on a thread (BufferedReader analog)
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * max(1, self.num_workers))
        sentinel = object()
        err: list = []

        def producer():
            try:
                for item in self._gen_batches():
                    q.put(item)
            except BaseException as e:  # noqa: BLE001
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
        if err:
            raise err[0]

    def _mp_context(self):
        """Pick a start method that cannot deadlock the XLA runtime.

        fork-ing a process whose XLA backend threads are already running is
        the classic dataloader deadlock (jax warns on it); fork is only
        used while the backend is untouched. Otherwise forkserver/spawn —
        whose children never inherit the runtime — are used, which
        requires the dataset/worker_init_fn to be picklable (checked by
        the caller)."""
        import multiprocessing as mp

        try:
            from jax._src import xla_bridge

            backend_up = xla_bridge.backends_are_initialized()
        except Exception:
            backend_up = True  # unknown → assume live, stay safe
        if not backend_up:
            return mp.get_context("fork")
        methods = mp.get_all_start_methods()
        return mp.get_context(
            "forkserver" if "forkserver" in methods else "spawn")

    def _make_ring(self, ctx, batches, nw):
        """Build the shared-memory ring when the transport is enabled; any
        failure (flag off, platform without shm, probe error) returns None
        and the epoch runs on the pipe transport."""
        if not (self.use_shared_memory and _shm_flag[0]):
            return None
        try:
            from .shm_ring import ShmRing, estimate_slot_bytes

            sample = _to_numpy_tree(self.dataset[batches[0][0]])
            slot_bytes = estimate_slot_bytes(
                sample, max(len(b) for b in batches))
            return ShmRing(ctx, n_slots=self.prefetch_factor * nw,
                           slot_bytes=slot_bytes)
        except Exception:  # noqa: BLE001 — fall back to pipes
            return None

    def _iter_multiprocess(self):
        """True multiprocess workers — the reference's dataloader_iter.py
        worker pool. Transport: workers write numpy batches into the
        shared-memory ring and ship only descriptors (shm_ring.py — the
        reference's flags.use_shared_memory path), falling back to pickled
        payloads per batch (non-numpy leaves, oversized batch) or per
        epoch (flag off, ring setup failure). Either way a reader thread
        pushes frames through the NATIVE blocking queue (core/csrc/
        ptpu_core.cc, the LoDTensorBlockingQueue analog) for bounded
        prefetch; the main iterator pops and decodes in sampler order."""
        from ..core import BlockingQueue
        from ..monitor import stats as _mstats
        from .shm_ring import (KIND_ERROR, KIND_PICKLE, KIND_SHM, dumps_desc,
                               loads_desc)

        ctx = self._mp_context()
        batches = list(self.batch_sampler)
        nw = max(1, self.num_workers)
        in_queues = [ctx.Queue() for _ in range(nw)]
        out_queue = ctx.Queue(maxsize=self.prefetch_factor * nw)
        ring = self._make_ring(ctx, batches, nw)
        stop_event = ctx.Event()
        ring_cfg = ring.worker_config() if ring is not None else None

        worker_init = getattr(self, "worker_init_fn", None)
        procs = [ctx.Process(
            target=_mp_worker_loop,
            args=(w, nw, self.dataset, worker_init, in_queues[w], out_queue,
                  ring_cfg, stop_event),
            daemon=True) for w in range(nw)]
        for p in procs:
            p.start()
        for seq, idxs in enumerate(batches):
            in_queues[seq % nw].put((seq, idxs))
        for q_ in in_queues:
            q_.put(None)

        # native bounded buffer: reader thread drains the mp queue into it;
        # a fixed 9-byte header (seq:int64, kind:u8) prefixes the payload —
        # pickled batch bytes are never re-serialized, shm descriptors stay
        # tiny (the batch bytes never touch a pipe at all)
        import struct

        native_q = BlockingQueue(capacity=self.prefetch_factor * nw)
        n_total = len(batches)

        def reader():
            # watch_local_trainers analog (reference launch_utils.py): poll
            # with a timeout and treat silently-dead workers as failure
            # instead of blocking forever on their never-arriving batches.
            import queue as _q

            done = 0
            while done < n_total:
                try:
                    seq, payload, err = out_queue.get(timeout=1.0)
                except _q.Empty:
                    if all(not p.is_alive() for p in procs):
                        dead = [p.exitcode for p in procs]
                        body = struct.pack(
                            "<qB", -1 & 0x7FFFFFFFFFFFFFFF, KIND_ERROR) + (
                            f"all workers exited (exitcodes={dead}) with "
                            f"{n_total - done} batches outstanding").encode()
                        try:
                            native_q.push(body)
                        except TimeoutError:
                            pass
                        return
                    continue
                done += 1
                if err is not None:
                    body = struct.pack("<qB", seq, KIND_ERROR) + err.encode()
                elif isinstance(payload, tuple) and payload[0] == "shm":
                    body = struct.pack("<qB", seq, KIND_SHM) + \
                        dumps_desc(payload[1])
                else:
                    body = struct.pack("<qB", seq, KIND_PICKLE) + payload
                try:
                    if not native_q.push(body):
                        return  # closed by consumer — stop draining
                except TimeoutError:
                    return

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()

        import pickle as pk
        pending = {}
        next_seq = 0
        try:
            for _ in range(n_total):
                item = native_q.pop()
                if item is None:
                    break
                seq, kind = struct.unpack_from("<qB", item)
                if kind == KIND_ERROR:
                    raise RuntimeError(
                        f"DataLoader worker failed: {item[9:].decode()}")
                if kind == KIND_SHM:
                    desc = loads_desc(item[9:])
                    # copy out + recycle the slot IMMEDIATELY even when the
                    # frame is out of order — a slot parked behind an
                    # earlier seq would starve the writers
                    pending[seq] = ring.read_batch(desc)
                    _mstats.SHM_BATCHES.add()
                    if desc[2]:
                        _mstats.SHM_RING_FULL.add()
                else:
                    pending[seq] = pk.loads(item[9:])
                while next_seq in pending:
                    yield _from_numpy_tree(pending.pop(next_seq))
                    next_seq += 1
        finally:
            stop_event.set()
            native_q.close()
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()
            rt.join(timeout=5)
            if ring is not None:
                ring.close()


from .prefetch import DevicePrefetcher, prefetch_to_device  # noqa: E402
