"""Shared-memory ring transport for multiprocess DataLoader workers.

The pipe transport pickles every collated batch and pushes the bytes
through an mp queue — one serialize copy, two pipe copies, one
deserialize copy per batch. The reference avoids this with shared memory
(fluid/dataloader/worker.py + flags.use_shared_memory: workers place
tensors in mmap'd segments and ship only descriptors). This module is
the TPU build's equivalent:

- the PARENT creates a ring of ``multiprocessing.shared_memory`` slots
  (``prefetch_factor * num_workers`` of them) and a free-slot queue;
- a WORKER claims a slot index (the queue token confers exclusive
  ownership — that IS the flow control), writes the batch's numpy leaves
  into the slot, and sends only a tiny descriptor (slot, leaf offsets/
  shapes/dtypes skeleton) through the normal result queue;
- the PARENT copies the leaves out and releases the slot index back to
  the free queue (slot recycling).

Only the parent ever CREATES or unlinks segments — attaching processes
unregister from the resource tracker (pre-3.13 Python registers on
attach too, and a worker's exit would otherwise unlink segments the
parent still uses). A batch whose leaves aren't plain numpy arrays (or
whose total size exceeds the slot) falls back to the pipe payload for
that batch only; platform errors during ring setup disable the ring for
the epoch. ``FLAGS_use_shared_memory=0`` turns the transport off.
"""
from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, List, Optional, Tuple

import numpy as np

_ALIGN = 64  # leaf offsets aligned for cheap vectorized copies

# descriptor kinds on the wire (the byte after the seq header)
KIND_PICKLE = 0   # payload is a pickled batch (pipe transport)
KIND_ERROR = 1    # payload is an error string
KIND_SHM = 2      # payload is a pickled (slot, skeleton, waited) descriptor


def _attach(name: str):
    """Attach an existing segment. Pre-3.13 SharedMemory registers with
    the resource tracker on attach too, but the tracker process is SHARED
    across the worker pool (inherited fd), so the duplicate registration
    is an idempotent set-add: the name stays tracked until the parent's
    unlink unregisters it once, and a crashed run still gets cleaned up
    at tracker shutdown. Unregistering here instead would cancel the
    parent's registration and double-unregister at close."""
    from multiprocessing import shared_memory

    return shared_memory.SharedMemory(name=name)


class ShmRing:
    """Parent-side ring owner: creates slots, recycles them, reads batches."""

    def __init__(self, ctx, n_slots: int, slot_bytes: int):
        from multiprocessing import shared_memory

        self.slot_bytes = int(slot_bytes)
        self.prefix = f"ptpu_{os.getpid()}_{uuid.uuid4().hex[:8]}_"
        self._segments = []
        try:
            for i in range(n_slots):
                seg = shared_memory.SharedMemory(
                    name=f"{self.prefix}{i}", create=True,
                    size=self.slot_bytes)
                # pre-touch: force physical page allocation NOW (setup,
                # amortized) instead of zero-fill faulting inside the
                # first worker writes (steady state)
                mv = np.ndarray((self.slot_bytes,), np.uint8,
                                buffer=seg.buf)
                mv[::4096] = 0
                del mv
                self._segments.append(seg)
        except Exception:
            self.close()
            raise
        self.free_slots = ctx.Queue()
        for i in range(n_slots):
            self.free_slots.put(i)

    def worker_config(self) -> dict:
        """Picklable config handed to each worker process."""
        return {"prefix": self.prefix, "slot_bytes": self.slot_bytes,
                "free_slots": self.free_slots}

    def read_batch(self, desc) -> Any:
        """Decode a KIND_SHM descriptor: copy leaves out of the slot, then
        recycle it. The copy is what bounds slot occupancy — the batch
        handed downstream owns its own memory."""
        slot, skeleton, _waited = desc
        buf = self._segments[slot].buf
        batch = _decode(skeleton, buf)
        self.free_slots.put(slot)
        return batch

    def close(self):
        for seg in getattr(self, "_segments", []):
            try:
                seg.close()
            except Exception:
                pass
            try:
                seg.unlink()
            except Exception:
                pass
        self._segments = []


# -- batch <-> slot encoding ------------------------------------------------
#
# The skeleton mirrors the batch pytree with every ndarray leaf replaced by
# ("__shm__", offset, shape, dtype_str); scalars ride along inline. A list
# of 1-D integer arrays (a CTR batch's ragged per-slot id lists) flattens
# to ONE offsets array + ONE values array — ("__shm_ragged__", kind,
# off_offsets, n_arrays, off_values, total, dtype_str) — two aligned
# copies instead of n tiny ones. A non-encodable leaf aborts the attempt
# (caller falls back to pickle, byte-identical to the pipe transport).

class _NotShmable(Exception):
    pass


def _ragged_candidate(tree) -> bool:
    return (len(tree) >= 2
            and all(isinstance(v, np.ndarray) and v.ndim == 1
                    and v.dtype.kind in "iu" for v in tree)
            and len({v.dtype for v in tree}) == 1)


def _plan(tree, offset: int) -> Tuple[Any, int, List[Tuple[int, np.ndarray]]]:
    if isinstance(tree, np.ndarray):
        off = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        if tree.dtype == object:
            raise _NotShmable
        return (("__shm__", off, tree.shape, tree.dtype.str),
                off + tree.nbytes, [(off, tree)])
    if isinstance(tree, (list, tuple)):
        if _ragged_candidate(tree):
            offsets = np.zeros(len(tree) + 1, np.int64)
            np.cumsum([len(v) for v in tree], out=offsets[1:])
            values = np.concatenate(tree)
            off_o = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
            off_v = (off_o + offsets.nbytes + _ALIGN - 1) \
                // _ALIGN * _ALIGN
            kind = "tuple" if isinstance(tree, tuple) else "list"
            return (("__shm_ragged__", kind, off_o, len(tree), off_v,
                     int(offsets[-1]), values.dtype.str),
                    off_v + values.nbytes,
                    [(off_o, offsets), (off_v, values)])
        out, writes = [], []
        for v in tree:
            sk, offset, w = _plan(v, offset)
            out.append(sk)
            writes.extend(w)
        return type(tree)(out), offset, writes
    if isinstance(tree, dict):
        out, writes = {}, []
        for k, v in tree.items():
            sk, offset, w = _plan(v, offset)
            out[k] = sk
            writes.extend(w)
        return out, offset, writes
    if tree is None or isinstance(tree, (bool, int, float, str, bytes,
                                         np.integer, np.floating)):
        return tree, offset, []
    raise _NotShmable


def encode_into(batch, buf, slot_bytes: int) -> Optional[Any]:
    """Write batch leaves into ``buf``; returns the skeleton, or None when
    the batch isn't shm-shippable (non-numpy leaf / doesn't fit)."""
    try:
        skeleton, total, writes = _plan(batch, 0)
    except _NotShmable:
        return None
    if total > slot_bytes:
        return None
    for off, arr in writes:
        dst = np.ndarray(arr.shape, arr.dtype, buffer=buf, offset=off)
        np.copyto(dst, arr)
    return skeleton


def _decode(skeleton, buf):
    if isinstance(skeleton, tuple) and len(skeleton) == 4 \
            and skeleton[0] == "__shm__":
        _, off, shape, dtype = skeleton
        src = np.ndarray(shape, np.dtype(dtype), buffer=buf, offset=off)
        return src.copy()
    if isinstance(skeleton, tuple) and len(skeleton) == 7 \
            and skeleton[0] == "__shm_ragged__":
        _, kind, off_o, n, off_v, total, dtype = skeleton
        offs = np.ndarray((n + 1,), np.int64, buffer=buf, offset=off_o)
        vals = np.ndarray((total,), np.dtype(dtype), buffer=buf,
                          offset=off_v)
        out = [vals[offs[i]:offs[i + 1]].copy() for i in range(n)]
        return tuple(out) if kind == "tuple" else out
    if isinstance(skeleton, (list, tuple)):
        return type(skeleton)(_decode(v, buf) for v in skeleton)
    if isinstance(skeleton, dict):
        return {k: _decode(v, buf) for k, v in skeleton.items()}
    return skeleton


class WorkerRing:
    """Worker-side view: claim slots, write batches, report ring pressure."""

    def __init__(self, cfg: dict):
        self.prefix = cfg["prefix"]
        self.slot_bytes = cfg["slot_bytes"]
        self.free_slots = cfg["free_slots"]
        self._attached: dict = {}

    def _buf(self, slot: int):
        shm = self._attached.get(slot)
        if shm is None:
            shm = _attach(f"{self.prefix}{slot}")
            self._attached[slot] = shm
        return shm.buf

    def put_batch(self, batch, stop_event) -> Optional[Tuple]:
        """Try to ship ``batch`` through the ring. Returns the descriptor
        tuple (slot, skeleton, waited) or None (caller uses pickle).
        ``waited`` marks that every slot was in flight when the worker got
        here — the parent surfaces it as the shm_ring_full gauge."""
        import queue as _q

        # cheap pre-check before claiming a slot: a non-shippable batch
        # must not consume (and then bounce) a ring token
        try:
            _, total, _ = _plan(batch, 0)
        except _NotShmable:
            return None
        if total > self.slot_bytes:
            return None

        waited = False
        try:
            slot = self.free_slots.get_nowait()
        except _q.Empty:
            waited = True
            while True:
                if stop_event is not None and stop_event.is_set():
                    return None
                try:
                    slot = self.free_slots.get(timeout=0.2)
                    break
                except _q.Empty:
                    continue
        try:
            skeleton = encode_into(batch, self._buf(slot), self.slot_bytes)
        except Exception:
            skeleton = None
        if skeleton is None:  # raced size estimate / platform error
            self.free_slots.put(slot)
            return None
        return (slot, skeleton, waited)

    def close(self):
        for shm in self._attached.values():
            try:
                shm.close()
            except Exception:
                pass
        self._attached = {}


def estimate_slot_bytes(sample, batch_size: int,
                        floor: int = 1 << 20,
                        headroom: float = 2.0) -> int:
    """Slot size from one probed sample: stacked-batch bytes x headroom
    (variable-length samples overflow into the per-batch pickle fallback,
    so the estimate only needs to be right for the common case)."""
    try:
        skel, total, _ = _plan(sample, 0)
        del skel
    except _NotShmable:
        total = 0
    est = int(total * max(1, batch_size) * headroom)
    # FLAGS_shm_slot_bytes rides the core/native cell (not a raw env
    # read) so set_flags can override it after import
    from ..core.native import shm_slot_bytes as _slot_bytes_flag

    if _slot_bytes_flag[0]:
        return max(int(_slot_bytes_flag[0]), 4096)
    return max(floor, est)


def dumps_desc(desc) -> bytes:
    return pickle.dumps(desc, protocol=pickle.HIGHEST_PROTOCOL)


def loads_desc(raw: bytes):
    return pickle.loads(raw)
