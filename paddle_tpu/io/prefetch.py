"""Double-buffered device prefetcher.

The DataLoader produces HOST batches; a training step consumes DEVICE
buffers. Without prefetch the host→device copy of batch N serializes
with step N-1's compute. :class:`DevicePrefetcher` wraps any batch
iterator and keeps ``size`` batches (default 2 — double buffering)
``jax.device_put`` ahead of the consumer, so the copy of batch N+1
overlaps step N: this is the framework-level version of the reference's
C++ BufferedReader async H2D stage, and of the device loop bench.py used
to carry privately.

When a parallel mesh is active (parallel.create_mesh) each array leaf is
placed with the mesh's batch sharding (leading dim over
``("data", "sharding")`` by default — the same default layout
DistributedTrainStep consumes), so the prefetcher also hides the
per-device scatter. Leaves whose leading dim doesn't divide the mesh (or
scalar leaves) fall back to single-device placement.

Gauges (paddle_tpu.monitor): ``prefetch_queue_depth`` tracks how many
batches are staged ahead (a persistently empty queue = input-bound),
``h2d_copy_ms`` accumulates host-side copy dispatch time. While tracing
is on, ``prefetch.h2d_copy`` and ``prefetch.wait`` spans land in the
chrome trace — ``tools/trace_report.py --top`` surfaces them in its
input-pipeline section.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Optional

import numpy as np

from ..framework.core import Tensor
from ..monitor import stats as _mstats
from ..monitor.trace import TRACING as _TRACING
from ..monitor.trace import get_writer as _trace_writer
from ..resilience import faults as _faults

__all__ = ["DevicePrefetcher", "prefetch_to_device"]


def _batch_sharding(mesh, batch_spec):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        from ..parallel.mesh import get_mesh

        mesh = get_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, batch_spec if batch_spec is not None
                         else P(("data", "sharding")))


class DevicePrefetcher:
    """Iterator wrapper: ``device_put`` batch N+1 while step N runs.

    Args:
      it: iterable of batches — pytrees whose leaves are Tensors, numpy
        arrays, jax arrays, or scalars. Structure is preserved; Tensor
        leaves come back as Tensors over committed device buffers.
      size: prefetch depth (2 = classic double buffering).
      mesh / batch_spec: device placement; default picks up the active
        mesh (parallel.get_mesh()) and shards the leading dim over
        ``("data", "sharding")``. No mesh → plain device_put.
    """

    def __init__(self, it: Iterable, size: int = 2, mesh=None,
                 batch_spec=None):
        self._it = it
        self.size = max(1, int(size))
        self._mesh = mesh
        self._batch_spec = batch_spec
        self._h2d_ms = 0.0

    def _put_leaf(self, x, sharding):
        import jax

        is_tensor = isinstance(x, Tensor)
        arr = x._data if is_tensor else x
        if sharding is not None and getattr(arr, "ndim", 0) >= 1:
            try:
                arr = jax.device_put(arr, sharding)
            except Exception:  # e.g. leading dim not divisible by the mesh
                arr = jax.device_put(arr)
        else:
            try:
                arr = jax.device_put(arr)
            except TypeError:  # non-array leaf (str, None, ...)
                return x
        if is_tensor:
            t = Tensor(arr, stop_gradient=x.stop_gradient, name=x.name)
            return t
        return arr

    def _put_batch(self, batch, sharding):
        if isinstance(batch, (list, tuple)):
            return type(batch)(self._put_batch(v, sharding) for v in batch)
        if isinstance(batch, dict):
            return {k: self._put_batch(v, sharding) for k, v in batch.items()}
        return self._put_leaf(batch, sharding)

    def __iter__(self):
        sharding = _batch_sharding(self._mesh, self._batch_spec)
        q: queue.Queue = queue.Queue(maxsize=self.size)
        sentinel = object()
        err: list = []

        def producer():
            try:
                for idx, batch in enumerate(self._it):
                    if _faults.ENABLED[0]:
                        # input_stall@step=N fault hook (resilience.faults):
                        # a sleeping producer starves the consumer exactly
                        # like a wedged storage read would
                        _faults.FAULTS.on_input(idx)
                    t0 = time.perf_counter()
                    staged = self._put_batch(batch, sharding)
                    dt = time.perf_counter() - t0
                    new_total = self._h2d_ms + dt * 1e3
                    _mstats.H2D_COPY_MS.add(int(new_total) - int(self._h2d_ms))
                    self._h2d_ms = new_total
                    if _TRACING[0]:
                        _trace_writer().add_complete(
                            "prefetch.h2d_copy", t0, dt, cat="input")
                    q.put(staged)
                    _mstats.PREFETCH_QUEUE_DEPTH.set(q.qsize())
            except BaseException as e:  # noqa: BLE001
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            if _TRACING[0] and q.empty():
                t0 = time.perf_counter()
                item = q.get()
                _trace_writer().add_complete(
                    "prefetch.wait", t0, time.perf_counter() - t0,
                    cat="input")
            else:
                item = q.get()
            if item is sentinel:
                break
            _mstats.PREFETCH_QUEUE_DEPTH.set(q.qsize())
            yield item
        t.join()
        _mstats.PREFETCH_QUEUE_DEPTH.set(0)
        if err:
            raise err[0]

    def __len__(self):
        return len(self._it)


def prefetch_to_device(it: Iterable, size: int = 2, mesh=None,
                       batch_spec=None):
    """Functional form of :class:`DevicePrefetcher` (returns a fresh
    iterator each call)."""
    return iter(DevicePrefetcher(it, size=size, mesh=mesh,
                                 batch_spec=batch_spec))
