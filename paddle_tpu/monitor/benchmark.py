"""FLAGS_benchmark per-op wall-time accumulation (reference
imperative/flags.cc FLAGS_benchmark + the per-op timing dump the tracer
prints when it is set).

`paddle.set_flags({"FLAGS_benchmark": 1})` flips the shared cell in
core.native; while it is on, `apply_op` feeds every eager dispatch's wall
time into :func:`record_op`. The table is host-side and cumulative until
:func:`benchmark_reset`.
"""
from __future__ import annotations

import threading

from ..core.native import benchmark as _benchmark_flag

__all__ = ["enabled", "record_op", "benchmark_rows", "benchmark_summary",
           "benchmark_reset"]

_lock = threading.Lock()
# name -> [calls, total_s, max_s, min_s]
_records: dict[str, list] = {}


def enabled() -> bool:
    return _benchmark_flag[0]


def record_op(name: str, seconds: float) -> None:
    with _lock:
        r = _records.get(name)
        if r is None:
            _records[name] = [1, seconds, seconds, seconds]
        else:
            r[0] += 1
            r[1] += seconds
            if seconds > r[2]:
                r[2] = seconds
            if seconds < r[3]:
                r[3] = seconds


def benchmark_rows() -> list:
    """Per-op rows sorted by total time, descending."""
    with _lock:
        rows = [
            {"op": n, "calls": r[0], "total": r[1], "avg": r[1] / r[0],
             "max": r[2], "min": r[3]}
            for n, r in _records.items()
        ]
    rows.sort(key=lambda r: -r["total"])
    return rows


def benchmark_summary(file=None) -> list:
    """Print the per-op wall-time table (FLAGS_benchmark dump analog);
    returns the rows."""
    rows = benchmark_rows()
    if rows:
        hdr = (f"{'Op':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"
               f"{'Max(s)':>12}{'Min(s)':>12}")
        print(hdr, file=file)
        for r in rows:
            print(f"{r['op']:<40}{r['calls']:>8}{r['total']:>12.6f}"
                  f"{r['avg']:>12.6f}{r['max']:>12.6f}{r['min']:>12.6f}",
                  file=file)
    return rows


def benchmark_reset() -> None:
    with _lock:
        _records.clear()
