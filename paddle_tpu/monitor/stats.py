"""Stat gauges (reference paddle/fluid/platform/monitor.h StatRegistry,
STAT_ADD/STAT_RESET macros).

A `Stat` is a named int64 gauge; the `StatRegistry` is the process-wide
thread-safe singleton holding them. Hot paths (framework.core.apply_op,
distributed collectives) hold module-level references to their pre-created
Stat objects so an increment is one lock + one add — no dict lookup, no
allocation, matching the reference's `STAT_INT64(name); STAT_ADD(...)`
static-registration idiom.

Stats live host-side only (they count host-visible events: dispatches,
compiles, cache hits, collective launches, NaN trips); device-side memory
gauges are filled on demand by :func:`update_memory_stats`.
"""
from __future__ import annotations

import re
import threading

__all__ = [
    "Stat", "StatRegistry", "stat_add", "stat_get", "stat_reset",
    "stat_names", "stat_snapshot", "reset_all_stats", "update_memory_stats",
    "DEFAULT_STATS",
    "Histogram", "DEFAULT_HISTOGRAMS", "hist_observe", "get_histogram",
    "histogram_snapshot", "hist_delta", "hist_quantile", "prometheus_text",
]


class Stat:
    """One named int64 counter/gauge (reference monitor.h StatValue)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, delta: int = 1) -> None:
        with self._lock:
            self._value += delta

    # reference StatValue::increase/decrease
    increase = add

    def decrease(self, delta: int = 1) -> None:
        self.add(-delta)

    def set(self, value: int) -> None:
        with self._lock:
            self._value = int(value)

    def get(self) -> int:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def __repr__(self):
        return f"Stat({self.name}={self._value})"


# log2-spaced default bucket bounds (milliseconds): 0.125ms .. 8.192s.
# Fixed and shared by every default histogram so cross-metric quantile
# comparisons and the bench agreement gate read off one resolution —
# "within bucket resolution" means within one factor-of-2 bucket.
DEFAULT_BUCKETS_MS = tuple(2.0 ** k for k in range(-3, 14))


class Histogram:
    """Fixed-bucket latency histogram (Prometheus histogram semantics:
    cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``).

    Buckets are log-spaced and FIXED at construction — observation is
    one lock + one bisect-free linear scan over ~17 bounds (cheap next
    to the time.monotonic() call that produced the sample), and two
    snapshots diff cleanly because the bounds never move.
    """

    __slots__ = ("name", "bounds", "_counts", "_count", "_sum", "_lock")

    def __init__(self, name: str, bounds=DEFAULT_BUCKETS_MS):
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(f"histogram bounds must be strictly "
                             f"increasing, got {bounds}")
        self._counts = [0] * (len(self.bounds) + 1)   # +1 = +Inf overflow
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v

    def snapshot(self) -> dict:
        """{"bounds", "counts" (per-bucket, NON-cumulative, +Inf last),
        "count", "sum"} — a value object two of which diff cleanly."""
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self._counts),
                    "count": self._count, "sum": self._sum}

    def quantile(self, q: float) -> float:
        return hist_quantile(self.snapshot(), q)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0

    def __repr__(self):
        return f"Histogram({self.name}, count={self._count})"


def hist_delta(before: dict, after: dict) -> dict:
    """Snapshot difference (same bounds): the observations made between
    the two snapshots — how bench scopes a histogram to one run leg."""
    if before["bounds"] != after["bounds"]:
        raise ValueError("histogram snapshots have different bounds")
    return {"bounds": list(after["bounds"]),
            "counts": [a - b for a, b in zip(after["counts"],
                                             before["counts"])],
            "count": after["count"] - before["count"],
            "sum": after["sum"] - before["sum"]}


def hist_quantile(snap: dict, q: float) -> float:
    """Quantile estimate from a snapshot: linear interpolation inside
    the bucket where the cumulative count crosses ``q`` (Prometheus
    ``histogram_quantile`` semantics; the +Inf bucket clamps to the last
    finite bound). NaN-free: an empty snapshot returns 0.0."""
    count = snap["count"]
    if count <= 0:
        return 0.0
    rank = q * count
    cum = 0
    lo = 0.0
    for i, c in enumerate(snap["counts"]):
        nxt = cum + c
        if nxt >= rank and c > 0:
            if i >= len(snap["bounds"]):
                return float(snap["bounds"][-1])    # +Inf bucket: clamp
            hi = snap["bounds"][i]
            frac = (rank - cum) / c
            return float(lo + (hi - lo) * frac)
        cum = nxt
        if i < len(snap["bounds"]):
            lo = snap["bounds"][i]
    return float(snap["bounds"][-1])


class StatRegistry:
    """Thread-safe singleton registry of Stats (monitor.h StatRegistry)."""

    _instance = None
    _instance_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    def __init__(self):
        self._stats: dict[str, Stat] = {}
        self._hists: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def get_stat(self, name: str) -> Stat:
        s = self._stats.get(name)
        if s is None:
            with self._lock:
                s = self._stats.setdefault(name, Stat(name))
        return s

    def get_histogram(self, name: str,
                      bounds=DEFAULT_BUCKETS_MS) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram(name, bounds))
        return h

    def histogram_snapshot(self) -> dict:
        with self._lock:
            hists = sorted(self._hists.items())
        return {n: h.snapshot() for n, h in hists}

    def add(self, name: str, delta: int = 1) -> None:
        self.get_stat(name).add(delta)

    def get(self, name: str) -> int:
        return self.get_stat(name).get()

    def reset(self, name: str) -> None:
        self.get_stat(name).reset()

    def reset_all(self) -> None:
        with self._lock:
            for s in self._stats.values():
                s.reset()
            for h in self._hists.values():
                h.reset()

    def names(self):
        with self._lock:
            return sorted(self._stats)

    def snapshot(self) -> dict:
        with self._lock:
            return {n: s.get() for n, s in sorted(self._stats.items())}


_registry = StatRegistry.instance()


def stat_add(name: str, delta: int = 1) -> None:
    _registry.add(name, delta)


def stat_get(name: str) -> int:
    return _registry.get(name)


def stat_reset(name: str) -> None:
    _registry.reset(name)


def stat_names():
    return _registry.names()


def stat_snapshot() -> dict:
    return _registry.snapshot()


def reset_all_stats() -> None:
    _registry.reset_all()


def hist_observe(name: str, value: float) -> None:
    _registry.get_histogram(name).observe(value)


def get_histogram(name: str) -> Histogram:
    return _registry.get_histogram(name)


def histogram_snapshot() -> dict:
    return _registry.histogram_snapshot()


# -- pre-registered stats (the subsystem's standing dashboard) --------------
#
# Hot paths import these module-level handles directly; everything else
# reads them by name through stat_get.

DEFAULT_STATS = (
    "op_dispatch",        # apply_op eager dispatches
    "jit_cache_hit",      # op-level jit cache hits (PreparedOp-cache analog)
    "jit_cache_miss",     # op-level jit cache misses
    "jit_compile",        # new jax.jit wrappers built (one per miss)
    "grad_jit_hit",       # grad-enabled dispatch: cached jitted-VJP hits
    "grad_jit_miss",      # grad-enabled dispatch: cache misses (new aval key)
    "grad_jit_compile",   # new fwd+vjp jit pairs built (one per miss)
    "collective_calls",   # distributed.* collective API launches
    "train_steps",        # compiled/eager training steps completed
    "nan_inf_trips",      # FLAGS_check_nan_inf violations raised
    "host_memory_bytes",  # gauge: peak host RSS (update_memory_stats)
    "device_memory_bytes",  # gauge: device bytes in use (update_memory_stats)
    # input-and-step fast path (ISSUE 3)
    "prefetch_queue_depth",  # gauge: batches staged ahead by DevicePrefetcher
    "h2d_copy_ms",        # cumulative host->device copy dispatch time (ms)
    "shm_ring_full",      # DataLoader shm batches that waited for a free slot
    "shm_batches",        # batches shipped via the shared-memory transport
    "step_async_syncs",   # async-step loss/metric materializations (blocking reads)
    # serving engine (ISSUE 4)
    "serving_queue_depth",     # gauge: requests waiting for a cache slot
    "serving_slot_occupancy",  # gauge: KV-cache slots currently generating
    "serving_prefill_ms",      # cumulative prompt-prefill wall time (ms)
    "serving_decode_ms",       # cumulative batched decode-tick wall time (ms)
    "serving_tokens_per_s",    # gauge: recent generation rate (tokens/s)
    "serving_evictions",       # sequences evicted from slots (eos/len/deadline/cancel)
    # paged KV cache (ISSUE 7)
    "kv_blocks_free",          # gauge: pool blocks on the free list
    "kv_blocks_used",          # gauge: pool blocks owned by live slots
    "kv_fragmentation",        # gauge: % of used-block capacity holding no live token
    "serving_preemptions",     # slots preempted back to the queue on pool exhaustion
    # self-healing training (ISSUE 5)
    "faults_injected",        # FLAGS_fault_inject faults actually fired
    "sentinel_trips",         # in-jit health verdict trips observed by the guardian
    "rollbacks",              # guardian rewinds to the host snapshot
    "preempt_saves",          # SIGTERM-forced priority checkpoint saves
    "watchdog_stalls",        # stalled-step detections by the watchdog thread
    "guardian_heartbeat_ms",  # gauge: monotonic ms of the last guarded step
    # Pallas kernel library + comm/compute overlap (ISSUE 6)
    "fused_optimizer_steps",  # fused (flat-buffer) optimizer steps taken
    "fused_kernel_calls",     # fused LN/MLP kernel dispatches (eager surface)
    "int8_matmul_calls",      # int8 weight-quantized matmul dispatches
    "grad_overlap_buckets",   # grad all-reduce buckets issued inside backward
    # speculative + multi-chip serving (ISSUE 10)
    "spec_proposed",           # draft tokens proposed by the speculative path
    "spec_accepted",           # draft tokens accepted by target verification
    "spec_acceptance_rate",    # gauge: % of proposed draft tokens accepted
    "serving_shards",          # gauge: "data"-axis shards the engine decodes over
    # fleet.auto hybrid-parallel planner (ISSUE 9)
    "plan_candidates_considered",   # legal candidates scored by the planner
    "zero_level",                   # gauge: chosen ZeRO stage (0-3)
    "pipeline_bubble_frac",         # gauge: chosen plan's bubble, ppm (1e-6)
    "planner_hbm_headroom_bytes",   # gauge: HBM budget minus chosen plan's need
    # radix prefix cache + serving front end (ISSUE 11)
    "prefix_matched_tokens",    # prompt tokens served from the radix tree
    "prefix_lookup_tokens",     # prompt tokens looked up at admission
    "prefix_hit_rate",          # gauge: % of looked-up prompt tokens matched
    "prefix_cache_blocks",      # gauge: pool blocks pinned by the radix tree
    "prefix_evictions",         # LRU-leaf tree nodes reclaimed to the pool
    "prefix_cow_copies",        # copy-on-write duplications of shared blocks
    "frontend_requests",        # HTTP generation requests accepted
    "frontend_429s",            # requests rejected by tenant admission (429)
    "frontend_queue_wait_ms",   # cumulative WFQ lane wait before submission
    "frontend_active_streams",  # gauge: generation streams currently open
    "constrained_requests",     # requests decoding under a token-mask automaton
    "constrained_fallback_ticks",  # spec ticks dropped to the plain program
    # pod-level resilience (ISSUE 12)
    "pod_hosts_alive",          # gauge: hosts with a fresh, non-tombstoned lease
    "elastic_resizes",          # pod resizes (replan+reshard+resume) after host loss
    "serving_watchdog_trips",   # serving sentinel verdicts (NaN tick / latency stall)
    "serving_watchdog_restarts",  # engine restarts from the last healthy state
    # overload-hardened serving (ISSUE 13)
    "serving_deadline_sheds",   # requests shed deadline-expired BEFORE any prefill
    "frontend_load_sheds",      # HTTP requests answered 503 (overload/deadline shed)
    "brownout_rung",            # gauge: current degradation-ladder rung (0=healthy)
    "brownout_steps",           # ladder transitions (up or down) taken
    "router_failovers",         # streams requeued to a survivor replica
    "serving_replicas_healthy",  # gauge: routable replicas behind the EngineRouter
    # elastic replica lifecycle (ISSUE 14)
    "serving_replicas_target",   # gauge: replica count the supervisor steers toward
    "serving_replica_restarts",  # replicas respawned after death/wedge/watchdog abort
    "serving_scale_events",      # autoscale transitions (grow or drain-shrink) completed
    "prefix_warm_tokens",        # prompt tokens replayed to re-warm a rejoined radix tree
    # sparse embedding / recommender stack (ISSUE 16)
    "embedding_lookup_ids",      # ids resolved through sparse lookup paths
    "embedding_unique_ratio",    # gauge: unique/total ids in the last batch, ppm
    "embedding_exchange_bytes",  # all-to-all bytes moved by sharded lookups
    "sparse_rows_touched",       # table rows updated by sparse optimizer steps
    # kernel autotuner + fp8 path (ISSUE 17)
    "autotune_hits",          # block configs served from the autotune cache
    "autotune_misses",        # cache misses that triggered a trial sweep
    "autotune_trials_ms",     # cumulative wall ms spent timing trial configs
    "fused_kernel_fallbacks",  # Pallas entries that fell back to composed jnp
    "fp8_matmul_calls",       # fp8 (e4m3) matmul dispatches
    # mixture-of-experts serving stats (ISSUE 18)
    "moe_expert_load",        # gauge: busiest-expert share of routed tokens, ppm
    "moe_tokens_dropped",     # routed assignments dropped past expert capacity
    # cross-host serving fleet (ISSUE 19)
    "fleet_hosts",            # gauge: fleet hosts with a fresh heartbeat
    "fleet_replicas",         # gauge: remote replica proxies attached to the router
    "fleet_kv_transfer_bytes",  # KV block bytes streamed prefill-host -> decode-host
    "fleet_kv_exports",       # prefix exports served by prefill-role replicas
    "fleet_kv_imports",       # prefix imports spliced into decode-role pools
    "fleet_prefill_routed",   # requests whose prefill ran on a prefill-role host
    "fleet_direct_fallbacks",  # disaggregated submits that fell back to direct decode
    "fleet_reroutes",         # host-loss events that re-routed streams to survivors
    "fleet_prewarms",         # replicas pre-warmed by the arrival-rate forecaster
    "rpc_calls",              # RPC round trips issued by remote replica proxies
    "rpc_errors",             # RPC round trips that failed (transport or remote)
    # fleet network fault tolerance (ISSUE 20)
    "rpc_retries",            # idempotent RPC calls re-sent after a transport error
    "rpc_breaker_state",      # gauge: per-peer circuit breakers currently OPEN
    "rpc_deadline_sheds",     # frames shed by the receiver: deadline already expired
    "fleet_kv_chunks_streamed",  # KV chunks shipped by the resumable streaming path
    "fleet_kv_resume_tails",  # decode-side local tail prefills after a mid-stream loss
    "flight_collects",        # fleet-wide flight-recorder collection sweeps
)

for _n in DEFAULT_STATS:
    _registry.get_stat(_n)

OP_DISPATCH = _registry.get_stat("op_dispatch")
JIT_CACHE_HIT = _registry.get_stat("jit_cache_hit")
JIT_CACHE_MISS = _registry.get_stat("jit_cache_miss")
JIT_COMPILE = _registry.get_stat("jit_compile")
GRAD_JIT_HIT = _registry.get_stat("grad_jit_hit")
GRAD_JIT_MISS = _registry.get_stat("grad_jit_miss")
GRAD_JIT_COMPILE = _registry.get_stat("grad_jit_compile")
COLLECTIVE_CALLS = _registry.get_stat("collective_calls")
TRAIN_STEPS = _registry.get_stat("train_steps")
NAN_INF_TRIPS = _registry.get_stat("nan_inf_trips")
HOST_MEMORY_BYTES = _registry.get_stat("host_memory_bytes")
DEVICE_MEMORY_BYTES = _registry.get_stat("device_memory_bytes")
PREFETCH_QUEUE_DEPTH = _registry.get_stat("prefetch_queue_depth")
H2D_COPY_MS = _registry.get_stat("h2d_copy_ms")
SHM_RING_FULL = _registry.get_stat("shm_ring_full")
SHM_BATCHES = _registry.get_stat("shm_batches")
STEP_ASYNC_SYNCS = _registry.get_stat("step_async_syncs")
SERVING_QUEUE_DEPTH = _registry.get_stat("serving_queue_depth")
SERVING_SLOT_OCCUPANCY = _registry.get_stat("serving_slot_occupancy")
SERVING_PREFILL_MS = _registry.get_stat("serving_prefill_ms")
SERVING_DECODE_MS = _registry.get_stat("serving_decode_ms")
SERVING_TOKENS_PER_S = _registry.get_stat("serving_tokens_per_s")
SERVING_EVICTIONS = _registry.get_stat("serving_evictions")
KV_BLOCKS_FREE = _registry.get_stat("kv_blocks_free")
KV_BLOCKS_USED = _registry.get_stat("kv_blocks_used")
KV_FRAGMENTATION = _registry.get_stat("kv_fragmentation")
SERVING_PREEMPTIONS = _registry.get_stat("serving_preemptions")
FAULTS_INJECTED = _registry.get_stat("faults_injected")
SENTINEL_TRIPS = _registry.get_stat("sentinel_trips")
ROLLBACKS = _registry.get_stat("rollbacks")
PREEMPT_SAVES = _registry.get_stat("preempt_saves")
WATCHDOG_STALLS = _registry.get_stat("watchdog_stalls")
GUARDIAN_HEARTBEAT_MS = _registry.get_stat("guardian_heartbeat_ms")
FUSED_OPTIMIZER_STEPS = _registry.get_stat("fused_optimizer_steps")
FUSED_KERNEL_CALLS = _registry.get_stat("fused_kernel_calls")
INT8_MATMUL_CALLS = _registry.get_stat("int8_matmul_calls")
GRAD_OVERLAP_BUCKETS = _registry.get_stat("grad_overlap_buckets")
SPEC_PROPOSED = _registry.get_stat("spec_proposed")
SPEC_ACCEPTED = _registry.get_stat("spec_accepted")
SPEC_ACCEPTANCE_RATE = _registry.get_stat("spec_acceptance_rate")
SERVING_SHARDS = _registry.get_stat("serving_shards")
PLAN_CANDIDATES_CONSIDERED = _registry.get_stat("plan_candidates_considered")
ZERO_LEVEL = _registry.get_stat("zero_level")
PIPELINE_BUBBLE_FRAC = _registry.get_stat("pipeline_bubble_frac")
PLANNER_HBM_HEADROOM_BYTES = _registry.get_stat("planner_hbm_headroom_bytes")
POD_HOSTS_ALIVE = _registry.get_stat("pod_hosts_alive")
ELASTIC_RESIZES = _registry.get_stat("elastic_resizes")
SERVING_WATCHDOG_TRIPS = _registry.get_stat("serving_watchdog_trips")
SERVING_WATCHDOG_RESTARTS = _registry.get_stat("serving_watchdog_restarts")
PREFIX_MATCHED_TOKENS = _registry.get_stat("prefix_matched_tokens")
PREFIX_LOOKUP_TOKENS = _registry.get_stat("prefix_lookup_tokens")
PREFIX_HIT_RATE = _registry.get_stat("prefix_hit_rate")
PREFIX_CACHE_BLOCKS = _registry.get_stat("prefix_cache_blocks")
PREFIX_EVICTIONS = _registry.get_stat("prefix_evictions")
PREFIX_COW_COPIES = _registry.get_stat("prefix_cow_copies")
FRONTEND_REQUESTS = _registry.get_stat("frontend_requests")
FRONTEND_429S = _registry.get_stat("frontend_429s")
FRONTEND_QUEUE_WAIT_MS = _registry.get_stat("frontend_queue_wait_ms")
FRONTEND_ACTIVE_STREAMS = _registry.get_stat("frontend_active_streams")
CONSTRAINED_REQUESTS = _registry.get_stat("constrained_requests")
CONSTRAINED_FALLBACK_TICKS = _registry.get_stat("constrained_fallback_ticks")
SERVING_DEADLINE_SHEDS = _registry.get_stat("serving_deadline_sheds")
FRONTEND_LOAD_SHEDS = _registry.get_stat("frontend_load_sheds")
BROWNOUT_RUNG = _registry.get_stat("brownout_rung")
BROWNOUT_STEPS = _registry.get_stat("brownout_steps")
ROUTER_FAILOVERS = _registry.get_stat("router_failovers")
SERVING_REPLICAS_HEALTHY = _registry.get_stat("serving_replicas_healthy")
SERVING_REPLICAS_TARGET = _registry.get_stat("serving_replicas_target")
SERVING_REPLICA_RESTARTS = _registry.get_stat("serving_replica_restarts")
SERVING_SCALE_EVENTS = _registry.get_stat("serving_scale_events")
PREFIX_WARM_TOKENS = _registry.get_stat("prefix_warm_tokens")
EMBEDDING_LOOKUP_IDS = _registry.get_stat("embedding_lookup_ids")
EMBEDDING_UNIQUE_RATIO = _registry.get_stat("embedding_unique_ratio")
EMBEDDING_EXCHANGE_BYTES = _registry.get_stat("embedding_exchange_bytes")
SPARSE_ROWS_TOUCHED = _registry.get_stat("sparse_rows_touched")
AUTOTUNE_HITS = _registry.get_stat("autotune_hits")
AUTOTUNE_MISSES = _registry.get_stat("autotune_misses")
AUTOTUNE_TRIALS_MS = _registry.get_stat("autotune_trials_ms")
FUSED_KERNEL_FALLBACKS = _registry.get_stat("fused_kernel_fallbacks")
FP8_MATMUL_CALLS = _registry.get_stat("fp8_matmul_calls")
MOE_EXPERT_LOAD = _registry.get_stat("moe_expert_load")
MOE_TOKENS_DROPPED = _registry.get_stat("moe_tokens_dropped")
FLEET_HOSTS = _registry.get_stat("fleet_hosts")
FLEET_REPLICAS = _registry.get_stat("fleet_replicas")
FLEET_KV_TRANSFER_BYTES = _registry.get_stat("fleet_kv_transfer_bytes")
FLEET_KV_EXPORTS = _registry.get_stat("fleet_kv_exports")
FLEET_KV_IMPORTS = _registry.get_stat("fleet_kv_imports")
FLEET_PREFILL_ROUTED = _registry.get_stat("fleet_prefill_routed")
FLEET_DIRECT_FALLBACKS = _registry.get_stat("fleet_direct_fallbacks")
FLEET_REROUTES = _registry.get_stat("fleet_reroutes")
FLEET_PREWARMS = _registry.get_stat("fleet_prewarms")
RPC_CALLS = _registry.get_stat("rpc_calls")
RPC_ERRORS = _registry.get_stat("rpc_errors")
RPC_RETRIES = _registry.get_stat("rpc_retries")
RPC_BREAKER_STATE = _registry.get_stat("rpc_breaker_state")
RPC_DEADLINE_SHEDS = _registry.get_stat("rpc_deadline_sheds")
FLEET_KV_CHUNKS_STREAMED = _registry.get_stat("fleet_kv_chunks_streamed")
FLEET_KV_RESUME_TAILS = _registry.get_stat("fleet_kv_resume_tails")
FLIGHT_COLLECTS = _registry.get_stat("flight_collects")


# -- pre-registered latency histograms (ISSUE 15) ---------------------------
#
# Recorded AT THE SOURCE (engine scheduler / frontend dispatcher), so the
# p50/p99 numbers bench.py used to hand-collect are live, scrapeable
# series under GET /metrics. All share DEFAULT_BUCKETS_MS.

DEFAULT_HISTOGRAMS = (
    ("serving_first_token_ms",
     "submit-to-first-token latency per request (ms)"),
    ("serving_per_token_ms",
     "steady-state inter-token latency per request, "
     "(t_last - t_first)/(n-1) (ms)"),
    ("serving_queue_wait_ms",
     "queue wait before work starts: WFQ lane wait and engine "
     "admission wait (ms)"),
    ("serving_decode_tick_ms",
     "batched decode tick wall latency (ms)"),
    ("serving_prefill_chunk_ms",
     "prefill work quantum wall latency: one chunk (paged) or one "
     "whole-prompt prefill (fixed) (ms)"),
    ("moe_expert_share_pct",
     "per-expert share of routed assignments per decode tick (%) — "
     "one observation per expert per tick, so the spread IS the "
     "imbalance (uniform router: all mass at 100/E)"),
    ("fleet_kv_transfer_ms",
     "prefill-host -> decode-host KV block stream wall latency per "
     "prompt: export + transport + pool splice (ms)"),
    ("fleet_arrival_gap_ms",
     "inter-arrival gap between fleet submissions (ms) — the "
     "arrival-rate series the pre-warm forecaster reads (rps = "
     "1000/median gap)"),
    ("rpc_call_ms",
     "remote-replica RPC round-trip wall latency (ms)"),
)

HISTOGRAM_HELP = dict(DEFAULT_HISTOGRAMS)

for _n, _ in DEFAULT_HISTOGRAMS:
    _registry.get_histogram(_n)

SERVING_FIRST_TOKEN_MS = _registry.get_histogram("serving_first_token_ms")
SERVING_PER_TOKEN_MS = _registry.get_histogram("serving_per_token_ms")
SERVING_QUEUE_WAIT_MS = _registry.get_histogram("serving_queue_wait_ms")
SERVING_DECODE_TICK_MS = _registry.get_histogram("serving_decode_tick_ms")
SERVING_PREFILL_CHUNK_MS = _registry.get_histogram(
    "serving_prefill_chunk_ms")
MOE_EXPERT_SHARE_PCT = _registry.get_histogram("moe_expert_share_pct")
FLEET_KV_TRANSFER_MS = _registry.get_histogram("fleet_kv_transfer_ms")
FLEET_ARRIVAL_GAP_MS = _registry.get_histogram("fleet_arrival_gap_ms")
RPC_CALL_MS = _registry.get_histogram("rpc_call_ms")


# -- Prometheus text exposition (ISSUE 15 satellite) ------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str = "paddle_tpu_") -> str:
    """Sanitize to a legal Prometheus metric name: invalid characters
    (the per-axis gauges' ``.``, benchmark rows' ``@``) become ``_``,
    and a leading digit is prefixed."""
    n = _PROM_BAD.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return prefix + n


def _prom_num(v: float) -> str:
    """Format a float the Prometheus text format accepts (no trailing
    noise: 0.125 -> '0.125', 8192.0 -> '8192')."""
    return format(float(v), "g")


def prometheus_text(prefix: str = "paddle_tpu_") -> str:
    """The full registry in Prometheus text exposition format 0.0.4:
    every gauge with ``# HELP``/``# TYPE``, every histogram as
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count`` —
    what GET /metrics serves."""
    lines = []
    for name, value in stat_snapshot().items():
        m = _prom_name(name, prefix)
        lines.append(f"# HELP {m} int64 gauge {name}")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {int(value)}")
    for name, snap in histogram_snapshot().items():
        m = _prom_name(name, prefix)
        help_txt = HISTOGRAM_HELP.get(name, f"latency histogram {name}")
        lines.append(f"# HELP {m} {help_txt}")
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for bound, c in zip(snap["bounds"], snap["counts"]):
            cum += c
            lines.append(f'{m}_bucket{{le="{_prom_num(bound)}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f"{m}_sum {_prom_num(snap['sum'])}")
        lines.append(f"{m}_count {snap['count']}")
    return "\n".join(lines) + "\n"


# per-mesh-axis device-memory gauges published by the last
# update_memory_stats call ("device_memory_bytes.<axis>"); tracked so a
# refresh can zero the axes that disappeared (mesh torn down, buffers freed)
_mem_axis_gauges: set = set()


def _buffer_axes(arr) -> set:
    """Mesh axes a live buffer is sharded over (empty = replicated /
    single-device)."""
    spec = getattr(getattr(arr, "sharding", None), "spec", None)
    axes = set()
    if spec is not None:
        for part in spec:
            if part is None:
                continue
            for ax in (part if isinstance(part, (tuple, list)) else (part,)):
                if ax is not None:
                    axes.add(str(ax))
    return axes


def update_memory_stats() -> dict:
    """Refresh the host/device memory gauges and return {name: bytes}.

    Host side reads the process peak RSS; device side sums
    ``bytes_in_use`` over visible jax devices (not every backend reports
    memory_stats — missing values leave the gauge unchanged). Device
    bytes are additionally SPLIT PER MESH AXIS: every live buffer's size
    is attributed to the mesh axis (or axes) its PartitionSpec shards it
    over — ``device_memory_bytes.data``, ``.model``, ... — with
    unsharded buffers under ``device_memory_bytes.replicated``, so a
    memory regression can be pinned to the parallelism dimension that
    grew (ROADMAP monitor follow-up).
    """
    out = {}
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        HOST_MEMORY_BYTES.set(int(rss_kb) * 1024)
    except Exception:
        pass
    try:
        import jax

        total = 0
        seen = False
        for d in jax.devices():
            ms = getattr(d, "memory_stats", None)
            if ms is None:
                continue
            try:
                total += int((ms() or {}).get("bytes_in_use", 0))
                seen = True
            except Exception:
                continue
        if seen:
            DEVICE_MEMORY_BYTES.set(total)
    except Exception:
        pass
    try:
        import jax

        per_axis: dict = {}
        for arr in jax.live_arrays():
            try:
                nbytes = int(arr.nbytes)
            except Exception:
                continue
            axes = _buffer_axes(arr) or {"replicated"}
            for ax in axes:
                per_axis[ax] = per_axis.get(ax, 0) + nbytes
        for ax, nbytes in per_axis.items():
            name = f"device_memory_bytes.{ax}"
            _registry.get_stat(name).set(nbytes)
            _mem_axis_gauges.add(name)
            out[name] = nbytes
        for name in _mem_axis_gauges - {
                f"device_memory_bytes.{ax}" for ax in per_axis}:
            _registry.get_stat(name).set(0)
            out[name] = 0
    except Exception:
        pass
    out["host_memory_bytes"] = HOST_MEMORY_BYTES.get()
    out["device_memory_bytes"] = DEVICE_MEMORY_BYTES.get()
    return out
