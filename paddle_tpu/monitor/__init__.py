"""paddle_tpu.monitor — observability subsystem.

Reference mapping:
- stat gauges           → paddle/fluid/platform/monitor.h (StatRegistry,
                          STAT_ADD/STAT_INT64 macros)
- chrome-trace export   → paddle/fluid/platform/profiler.cc (DeviceTracer
                          chrome://tracing JSON dump)
- FLAGS_benchmark       → paddle/fluid/imperative/flags.cc per-op timing
- TrainerMonitor        → per-step telemetry feeding hapi callbacks
                          (callbacks.py Monitor) and tools/scaling_report

Layering: this package depends only on the stdlib and core.native (the
flag cells), so the hot paths (framework.core, distributed.collective,
parallel.train_step) can import it without cycles. Everything is
opt-out-by-default: with tracing off and FLAGS_benchmark=0 the only cost
in the dispatch path is counter increments.
"""
from .stats import (
    DEFAULT_STATS,
    Stat,
    StatRegistry,
    reset_all_stats,
    stat_add,
    stat_get,
    stat_names,
    stat_reset,
    stat_snapshot,
    update_memory_stats,
)
from .trace import (
    TraceWriter,
    get_writer,
    is_tracing,
    span,
    start_tracing,
    stop_tracing,
)
from .benchmark import (
    benchmark_reset,
    benchmark_rows,
    benchmark_summary,
)
from .trainer import TrainerMonitor

__all__ = [
    "Stat", "StatRegistry", "DEFAULT_STATS",
    "stat_add", "stat_get", "stat_reset", "stat_names", "stat_snapshot",
    "reset_all_stats", "update_memory_stats",
    "TraceWriter", "get_writer", "is_tracing", "span",
    "start_tracing", "stop_tracing",
    "benchmark_reset", "benchmark_rows", "benchmark_summary",
    "TrainerMonitor",
]
