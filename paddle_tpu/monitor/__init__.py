"""paddle_tpu.monitor — observability subsystem.

Reference mapping:
- stat gauges           → paddle/fluid/platform/monitor.h (StatRegistry,
                          STAT_ADD/STAT_INT64 macros)
- chrome-trace export   → paddle/fluid/platform/profiler.cc (DeviceTracer
                          chrome://tracing JSON dump)
- FLAGS_benchmark       → paddle/fluid/imperative/flags.cc per-op timing
- TrainerMonitor        → per-step telemetry feeding hapi callbacks
                          (callbacks.py Monitor) and tools/scaling_report

Observability v2 (ISSUE 15):
- histogram metrics    → stats.Histogram (log2 buckets, +count/+sum) +
                         stats.prometheus_text() — the GET /metrics body
- causal tracing       → tracectx.TraceContext / mint_trace + the
                         trace.py flow events ("s"/"t"/"f") that chain a
                         request's spans into one chrome timeline
- crash flight recorder→ flight.FlightRecorder: a bounded ring of recent
                         spans/gauge deltas dumped (pod-aware naming) at
                         the moment of failure, merged across hosts by
                         tools/trace_report.py

Layering: this package depends only on the stdlib and core.native (the
flag cells), so the hot paths (framework.core, distributed.collective,
parallel.train_step) can import it without cycles. Everything is
opt-out-by-default: with tracing off, no flight recorder armed and
FLAGS_benchmark=0 the only cost in the dispatch path is counter
increments.
"""
from .stats import (
    DEFAULT_HISTOGRAMS,
    DEFAULT_STATS,
    Histogram,
    Stat,
    StatRegistry,
    get_histogram,
    hist_delta,
    hist_observe,
    hist_quantile,
    histogram_snapshot,
    prometheus_text,
    reset_all_stats,
    stat_add,
    stat_get,
    stat_names,
    stat_reset,
    stat_snapshot,
    update_memory_stats,
)
from .trace import (
    TraceWriter,
    get_writer,
    is_tracing,
    recording,
    span,
    start_tracing,
    stop_tracing,
)
from .tracectx import TraceContext, mint_trace
from .flight import (
    FlightRecorder,
    arm_flight_recorder,
    disarm_flight_recorder,
    dump_flight,
    get_flight_recorder,
    host_id,
    set_host_id,
)
from .benchmark import (
    benchmark_reset,
    benchmark_rows,
    benchmark_summary,
)
from .trainer import TrainerMonitor

__all__ = [
    "Stat", "StatRegistry", "DEFAULT_STATS",
    "stat_add", "stat_get", "stat_reset", "stat_names", "stat_snapshot",
    "reset_all_stats", "update_memory_stats",
    "Histogram", "DEFAULT_HISTOGRAMS", "hist_observe", "get_histogram",
    "histogram_snapshot", "hist_delta", "hist_quantile", "prometheus_text",
    "TraceWriter", "get_writer", "is_tracing", "recording", "span",
    "start_tracing", "stop_tracing",
    "TraceContext", "mint_trace",
    "FlightRecorder", "arm_flight_recorder", "disarm_flight_recorder",
    "dump_flight", "get_flight_recorder", "set_host_id", "host_id",
    "benchmark_reset", "benchmark_rows", "benchmark_summary",
    "TrainerMonitor",
]
