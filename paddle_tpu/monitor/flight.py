"""Crash flight recorder (ISSUE 15): a bounded ring of the most recent
spans, gauge deltas and trace events per process, dumped as a
self-contained chrome-trace + JSON summary at the moment of failure.

Full tracing answers "what happened during the window I captured";
post-mortems need the opposite — "what were the last few seconds before
the crash I did not know was coming". The recorder is that black box:
once ARMED (:func:`arm_flight_recorder`) every ``monitor.trace.span``
and ``emit_*`` event is also appended to a fixed-capacity ring (oldest
events fall off), gauge DELTAS are interleaved as chrome counter events
every ``gauge_every`` appends (only gauges that moved — the ring stays
spans-dense), and :func:`dump_flight` serializes ring + final gauge
snapshot + a summary block to ``trace_dir``.

Dump triggers wired in this PR: the TrainGuardian watchdog stall path,
the serving watchdog (engine restart and budget exhaustion), the engine
scheduler abort, and the ReplicaSupervisor give-up rung. Each dump file
is POD-AWARE: named ``flight_<host>_<pid>_<seq>_<reason>.json`` with the
host id the elastic layer registered (:func:`set_host_id` — the
TrainGuardian's pod attachment sets it; standalone processes default to
``h0``), so multi-host dumps dropped into one directory merge into one
timeline via ``python -m tools.trace_report dump1.json dump2.json ...``
(events are re-tagged per-host pids; flow ids are pid-salted and stay
distinct).

Unarmed (the default) the only cost anywhere is one extra list-index
check in ``span()`` — every pinned bit-identical contract is preserved.
"""
from __future__ import annotations

import datetime
import json
import os
import threading
import time
from collections import deque
from typing import Optional

from .stats import stat_snapshot
from .trace import FLIGHT

__all__ = ["FlightRecorder", "arm_flight_recorder",
           "disarm_flight_recorder", "get_flight_recorder", "dump_flight",
           "set_host_id", "host_id"]

# pod-aware identity for dump naming (the elastic layer's host name);
# a list cell so setters reach every importer
_HOST = [os.environ.get("PADDLE_TPU_HOST_ID", "h0")]


def set_host_id(host: str) -> None:
    """Name this process's dumps after the elastic layer's host id."""
    _HOST[0] = str(host)


def host_id() -> str:
    return _HOST[0]


class FlightRecorder:
    """Fixed-capacity ring of chrome-trace events + gauge deltas."""

    def __init__(self, trace_dir: Optional[str] = None,
                 capacity: int = 4096, gauge_every: int = 64):
        self.trace_dir = trace_dir
        self.capacity = int(capacity)
        self.gauge_every = max(1, int(gauge_every))
        self.pid = os.getpid()
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._since_gauges = 0
        self._last_gauges: dict = {}
        self._dump_seq = 0

    # -- event sinks (signature-compatible with TraceWriter) -----------------
    def _append(self, ev: dict) -> None:
        with self._lock:
            self._ring.append(ev)
            self._since_gauges += 1
            due = self._since_gauges >= self.gauge_every
            if due:
                self._since_gauges = 0
        if due:
            self.note_gauges()

    def add_complete(self, name: str, ts: float, dur: float,
                     tid: Optional[int] = None, cat: str = "op",
                     args: Optional[dict] = None) -> None:
        ev = {"name": name, "ph": "X", "cat": cat, "pid": self.pid,
              "tid": threading.get_ident() & 0x7FFFFFFF if tid is None
              else tid,
              "ts": int(ts * 1e6), "dur": int(dur * 1e6)}
        if args:
            ev["args"] = dict(args)
        self._append(ev)

    def add_instant(self, name: str, ts: float, cat: str = "instant") -> None:
        self._append({"name": name, "ph": "i", "cat": cat, "pid": self.pid,
                      "tid": threading.get_ident() & 0x7FFFFFFF,
                      "ts": int(ts * 1e6)})

    def add_flow(self, ph: str, flow_id: int, ts: float,
                 name: str = "request", cat: str = "trace") -> None:
        ev = {"name": name, "ph": ph, "cat": cat, "pid": self.pid,
              "tid": threading.get_ident() & 0x7FFFFFFF,
              "ts": int(ts * 1e6), "id": int(flow_id)}
        if ph == "f":
            ev["bp"] = "e"
        self._append(ev)

    def note_gauges(self) -> None:
        """Append a counter event of the gauges that MOVED since the
        last sample — the ring's gauge-delta interleave."""
        snap = stat_snapshot()
        with self._lock:
            delta = {k: v for k, v in snap.items()
                     if self._last_gauges.get(k) != v}
            self._last_gauges = snap
            if delta:
                self._ring.append({
                    "name": "gauges", "ph": "C", "pid": self.pid, "tid": 0,
                    "ts": int(time.perf_counter() * 1e6), "args": delta})

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def events(self) -> list:
        with self._lock:
            return list(self._ring)

    # -- the dump ------------------------------------------------------------
    def dump(self, reason: str, trace_dir: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring + a final gauge snapshot + a summary block to
        ``flight_<host>_<pid>_<seq>_<reason>.json`` under ``trace_dir``
        (falling back to the recorder's). Returns the path, or None when
        no directory is configured. Never raises — a failing dump must
        not mask the failure being recorded."""
        d = trace_dir or self.trace_dir
        if not d:
            return None
        try:
            self.note_gauges()
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
                events = list(self._ring)
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in str(reason))[:48] or "dump"
            host = host_id()
            payload = {
                "traceEvents": events
                + [{"name": "process_name", "ph": "M", "pid": self.pid,
                    "args": {"name": f"{host} pid={self.pid}"}}],
                "displayTimeUnit": "ms",
                "flight": {
                    "reason": str(reason), "host": host, "pid": self.pid,
                    "seq": seq, "events": len(events),
                    "t_dump_us": int(time.perf_counter() * 1e6),
                    # human log timestamp for cross-host correlation
                    # (the event timeline itself stays on perf_counter)
                    "wall_time": datetime.datetime.now(
                        datetime.timezone.utc).isoformat(),
                    "gauges": stat_snapshot(),
                    **(extra or {}),
                },
            }
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{host}_{self.pid}_{seq:03d}_{safe}.json")
            with open(path, "w") as f:
                json.dump(payload, f)
            return path
        except Exception:  # noqa: BLE001 — diagnostics must never mask
            return None    # the failure that triggered them


# -- module surface (the armed recorder lives in trace.FLIGHT) --------------

def arm_flight_recorder(trace_dir: Optional[str] = None,
                        capacity: int = 4096,
                        gauge_every: int = 64) -> FlightRecorder:
    """Arm (or re-target) the process flight recorder. Idempotent: an
    already-armed recorder keeps its ring and only adopts a newly-given
    ``trace_dir`` — multiple engines/guardians in one process share one
    black box."""
    rec = FLIGHT[0]
    if rec is None:
        rec = FlightRecorder(trace_dir=trace_dir, capacity=capacity,
                             gauge_every=gauge_every)
        FLIGHT[0] = rec
    elif trace_dir is not None:
        rec.trace_dir = trace_dir
    return rec


def disarm_flight_recorder() -> None:
    FLIGHT[0] = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return FLIGHT[0]


def dump_flight(reason: str, trace_dir: Optional[str] = None,
                extra: Optional[dict] = None) -> Optional[str]:
    """Dump the armed recorder (no-op returning None when unarmed)."""
    rec = FLIGHT[0]
    if rec is None:
        return None
    return rec.dump(reason, trace_dir=trace_dir, extra=extra)
