"""Causal trace context for the serving fleet (ISSUE 15).

A :class:`TraceContext` is the Dapper-style identity one request carries
from HTTP admission to its last decoded token: a process-unique
``trace_id`` (the chrome-trace FLOW id — every event stamped with it is
drawn on one connected arrow chain), a parent/child span-id pair so
events nest causally rather than just temporally, and the list of
replica HOPS the request survived (failover adoption, supervisor
restart/rejoin) so a cross-replica timeline still reads as ONE request.

Who does what:

- the front end MINTS a context per generation request
  (:func:`mint_trace`) and emits the flow-START event at admission;
- every layer a request passes through (WFQ lane wait, engine
  admission/prefill, each prefill chunk, each decode tick it
  participates in, the failover hop) stamps its span with
  :meth:`TraceContext.args` and a flow STEP, becoming a child of the
  previous span;
- request completion emits the flow FINISH.

``tools/trace_report.py request_report`` groups events by ``trace`` and
prints the per-request critical path (lane wait vs prefill vs decode vs
stalls) plus the slowest-N breakdown; chrome://tracing renders the same
events as one connected per-request timeline across threads, replicas
and (merged flight dumps) hosts.

Context minting and propagation never touches sampling state — with
tracing off and no flight recorder armed the token stream is pinned
bit-identical (the context rides along but nothing reads it).
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import List, Optional, Tuple

__all__ = ["TraceContext", "mint_trace"]

# trace ids carry the pid in their high bits so flow chains from
# different processes stay distinct when flight dumps are merged
_seq = itertools.count(1)
_seq_lock = threading.Lock()


class TraceContext:
    """One request's causal identity: flow id + span lineage + hops."""

    __slots__ = ("trace_id", "parent_id", "span_id", "hops", "_n")

    def __init__(self, trace_id: int):
        self.trace_id = int(trace_id)
        self.parent_id = 0          # span id of the latest emitted span
        self.span_id = 0
        self.hops: List[Tuple[Optional[int], Optional[int]]] = []
        self._n = 0

    def child(self) -> Tuple[int, int]:
        """Allocate the next span id; returns (parent_id, span_id) and
        advances the lineage so the NEXT span parents off this one."""
        self._n += 1
        parent = self.span_id
        self.parent_id = parent
        self.span_id = self._n
        return parent, self._n

    def args(self, **extra) -> dict:
        """Span-args payload for the next event on this trace: allocates
        a child span id and merges any per-span extras."""
        parent, sid = self.child()
        out = {"trace": self.trace_id, "span": sid, "parent": parent}
        if self.hops:
            out["hop"] = len(self.hops)
        out.update(extra)
        return out

    def hop(self, from_replica: Optional[int],
            to_replica: Optional[int]) -> None:
        """Record a replica hop (failover adoption / rejoin replay)."""
        self.hops.append((from_replica, to_replica))

    def __repr__(self):
        return (f"TraceContext({self.trace_id:#x}, spans={self._n}, "
                f"hops={self.hops})")


def mint_trace() -> TraceContext:
    """New process-unique trace context (pid-salted flow id)."""
    with _seq_lock:
        n = next(_seq)
    return TraceContext(((os.getpid() & 0xFFFF) << 40) | n)
