"""Per-step training telemetry.

`TrainerMonitor` brackets each training step (``step_begin``/``step_end``)
and derives wall time, examples/s, and the per-step deltas of the hot-path
stats — recompiles (jit_compile), dispatches, collective launches. The
hapi `Monitor` callback and `tools/scaling_report.py` feed from it; it is
the host-side analog of the reference's benchmark per-step logging
(FLAGS_benchmark step dump + VisualDL scalars).
"""
from __future__ import annotations

import time

from . import stats

__all__ = ["TrainerMonitor"]

_TRACKED = ("jit_compile", "op_dispatch", "collective_calls",
            "grad_jit_compile")


class TrainerMonitor:
    """Step-time / throughput / recompile telemetry around a train loop."""

    def __init__(self):
        self.history: list[dict] = []
        self.step_idx = 0
        self._t0 = None
        self._marks = None

    def reset(self) -> None:
        self.history.clear()
        self.step_idx = 0
        self._t0 = None
        self._marks = None

    def step_begin(self) -> None:
        self._marks = tuple(stats.stat_get(n) for n in _TRACKED)
        self._t0 = time.perf_counter()

    def step_end(self, examples: int | None = None) -> dict:
        """Close the step; returns the telemetry dict (also appended to
        ``history``). Safe to call without step_begin (returns {})."""
        if self._t0 is None:
            return {}
        dt = time.perf_counter() - self._t0
        compiles, dispatches, collectives, grad_compiles = (
            stats.stat_get(n) - m for n, m in zip(_TRACKED, self._marks))
        tele = {
            "step": self.step_idx,
            "step_time_s": dt,
            "recompiles": compiles,
            "grad_recompiles": grad_compiles,
            "op_dispatches": dispatches,
            "collective_calls": collectives,
        }
        if examples:
            tele["examples_per_sec"] = examples / dt if dt > 0 else 0.0
        self.history.append(tele)
        self.step_idx += 1
        self._t0 = None
        self._marks = None
        stats.TRAIN_STEPS.add()
        return tele

    def summary(self) -> dict:
        """Aggregate over recorded steps. Mean step time excludes step 0
        when possible — the first step carries compilation."""
        if not self.history:
            return {"steps": 0}
        steady = self.history[1:] if len(self.history) > 1 else self.history
        times = [h["step_time_s"] for h in steady]
        out = {
            "steps": len(self.history),
            "first_step_time_s": self.history[0]["step_time_s"],
            "mean_step_time_s": sum(times) / len(times),
            "max_step_time_s": max(times),
            "total_recompiles": sum(h["recompiles"] for h in self.history),
            "total_grad_recompiles": sum(
                h.get("grad_recompiles", 0) for h in self.history),
        }
        ips = [h["examples_per_sec"] for h in steady
               if "examples_per_sec" in h]
        if ips:
            out["mean_examples_per_sec"] = sum(ips) / len(ips)
        return out
