"""Chrome-trace-event JSON exporter (reference platform/profiler.cc
GenEventKernelCudaElapsedTime / DeviceTracer dump → chrome://tracing).

`TraceWriter` accumulates trace events host-side and serializes the
chrome trace-event format (the `{"traceEvents": [...]}` envelope) that
Perfetto / chrome://tracing / `tools/trace_report.py` load directly —
independent of jax.profiler's TensorBoard plugin, so it works on any
backend.

The module-level writer plus the `TRACING` gate are the recording
switch the hot paths check: `apply_op` and `RecordEvent` test
``TRACING[0]`` (one list index) before paying for any span bookkeeping,
so an idle process records nothing and allocates nothing.

Timestamps are `time.perf_counter()` seconds converted to the format's
microseconds — one monotonic clock for every producer keeps spans from
different layers aligned on the same timeline.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["TraceWriter", "TRACING", "is_tracing", "start_tracing",
           "stop_tracing", "get_writer", "span"]

# shared mutable gate — hot paths read TRACING[0] directly
TRACING = [False]


class TraceWriter:
    """Thread-safe collector of chrome trace events."""

    def __init__(self, pid: int | None = None):
        self.pid = os.getpid() if pid is None else pid
        self._events: list[dict] = []
        self._lock = threading.Lock()

    # -- event constructors -------------------------------------------------
    def add_complete(self, name: str, ts: float, dur: float,
                     tid: int | None = None, cat: str = "op",
                     args: dict | None = None) -> None:
        """One "X" (complete) event; ts/dur in seconds on the perf_counter
        timeline."""
        ev = {
            "name": name, "ph": "X", "cat": cat, "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF if tid is None else tid,
            "ts": int(ts * 1e6), "dur": int(dur * 1e6),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_begin(self, name: str, ts: float, tid: int | None = None,
                  cat: str = "op") -> None:
        self._add_mark("B", name, ts, tid, cat)

    def add_end(self, name: str, ts: float, tid: int | None = None,
                cat: str = "op") -> None:
        self._add_mark("E", name, ts, tid, cat)

    def add_instant(self, name: str, ts: float, cat: str = "instant") -> None:
        self._add_mark("i", name, ts, None, cat)

    def _add_mark(self, ph, name, ts, tid, cat):
        with self._lock:
            self._events.append({
                "name": name, "ph": ph, "cat": cat, "pid": self.pid,
                "tid": threading.get_ident() & 0x7FFFFFFF if tid is None
                else tid,
                "ts": int(ts * 1e6),
            })

    def add_counter(self, name: str, ts: float, values: dict) -> None:
        """One "C" (counter) event — e.g. the stat gauges over time."""
        with self._lock:
            self._events.append({
                "name": name, "ph": "C", "pid": self.pid, "tid": 0,
                "ts": int(ts * 1e6), "args": dict(values),
            })

    def extend(self, events) -> None:
        with self._lock:
            self._events.extend(events)

    # -- access / export ----------------------------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def __len__(self):
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_json(self) -> str:
        return json.dumps({"traceEvents": self.events(),
                           "displayTimeUnit": "ms"})

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json())
        return path


_writer = TraceWriter()


def get_writer() -> TraceWriter:
    return _writer


def is_tracing() -> bool:
    return TRACING[0]


def start_tracing(clear: bool = True) -> TraceWriter:
    if clear:
        _writer.clear()
    TRACING[0] = True
    return _writer


def stop_tracing() -> TraceWriter:
    TRACING[0] = False
    return _writer


@contextlib.contextmanager
def span(name: str, cat: str = "op", args: dict | None = None):
    """Record a span around a block — free when tracing is off."""
    if not TRACING[0]:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _writer.add_complete(name, t0, time.perf_counter() - t0,
                             cat=cat, args=args)
